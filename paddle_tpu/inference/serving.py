"""Continuous-batching decode engine (parity: the reference's serving
decode path — phi ``fused_multi_transformer`` + ``masked_multihead_
attention``'s batched per-sequence caches, as driven by FastDeploy-style
servers; upgraded with a paged KV pool).

TPU-native shape discipline: ONE compiled decode program with a static
``[slots, 1]`` token batch serves the whole lifetime of the engine.
Sequences enter and leave *as data*: per-slot lengths, an active mask,
and (paged mode) block tables are device arrays the host scheduler
updates — no shape ever changes, so nothing recompiles. Prefill keeps
the same discipline: ONE compiled fixed-size ``[slots, prefill_chunk]``
program writes straight into the live cache at vector per-slot offsets,
driven in a host loop — compute ∝ suffix rounded to the chunk (not the
seq bucket), several queued requests' chunks pack into one call, and
everything dispatches behind the in-flight decode chunk. Admission
first consults the PREFIX CACHE (``prefix_cache.py``): the longest
cached block-aligned prompt prefix is shared into the slot (paged:
refcounted pages, copy-on-write; contiguous: copied blocks) and only
the suffix is prefilled. ``PT_FLAGS_prefill_chunk=0`` restores the
legacy per-bucket prefill — the parity oracle.
"""

from __future__ import annotations

import bisect
import collections
import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags, generation as G, observability
from ..core.functional import (
    extract_buffers,
    extract_params,
    functional_call,
)
from ..core.module import Layer
from .paged import PagedLayerCache, PagedState, PagePool, init_paged_pool
from .prefix_cache import ContigPrefixStore, PagedPrefixStore, block_hashes
from .resilience import (
    CORRUPT_SITES,
    RUNTIME_ERRORS,
    DegradationController,
    FaultInjector,
    InjectedFault,
)
from .spec_decode import Drafter, NgramDrafter

# trace-time compile accounting: each compiled-program body bumps its
# counter exactly once per jit SPECIALIZATION (python runs at trace
# time only) — the tests' compile-count guard reads deltas here to
# assert chunked prefill never re-specializes across prompt lengths
TRACE_COUNTS: collections.Counter = collections.Counter()

# trace-time shape notes, one per program: the MOST RECENT
# specialization's key arg shapes, recorded next to the compile-count
# bump (python runs at trace time only, so this is free at dispatch
# time). The runtime recompile watchdog attaches this to its
# FlightRecorder artifact — a post-seal recompile dump names the
# offending shapes, not just the program
TRACE_SHAPES: Dict[str, dict] = {}


def _shape_note(program: str, **args):
    """Record the traced args' shapes for ``program`` (called from
    inside jitted bodies, at trace time only)."""
    TRACE_SHAPES[program] = {
        k: tuple(getattr(v, "shape", ())) for k, v in args.items()}


@dataclass
class EngineConfig:
    max_slots: int = 4
    max_len: int = 1024
    seq_buckets: Sequence[int] = (64, 128, 256, 512, 1024)
    paged: bool = False
    # paged mode: tokens per KV page. Contiguous mode reuses it as the
    # prefix-cache block granularity (rolling-hash block length)
    page_size: int = 64
    n_pages: Optional[int] = None  # default: slots*max_len/page_size (+sink)
    # "auto" resolves through PT_FLAGS_kv_cache_dtype: bf16 on TPU
    # (halves decode KV traffic), fp32 elsewhere; explicit dtypes win.
    # "int8" builds quantized pools with per-row f32 scales alongside
    # (quantize-on-append, in-kernel dequant) — requires the chunked
    # prefill path and single-chip serving, both validated at init
    cache_dtype: object = "auto"
    # serving weight stream: "auto" resolves through
    # PT_FLAGS_serve_weight_dtype (default bf16 = the model's own
    # weights). int8/int4 group-wise weight-only quantization happens
    # at ENGINE INIT via quantize_model_weight_only — qweights+scales
    # are buffers, so they ride every compiled program as jit
    # arguments (the seam below) and dequantize in-kernel
    weight_dtype: str = "auto"
    # group size for the weight-only quantization's group-wise scales
    # (layers whose in_features don't divide it fall back to one
    # degenerate whole-column group, same rule as WeightOnlyLinear)
    weight_group_size: int = 128
    # quantize the CALLER'S model tree in place (frees the fp linears
    # as they are replaced — the right trade for a 7B model that fits
    # HBM only once). Default False: the engine deep-copies first, so
    # the caller's model stays servable at full precision (A/B benches
    # and tests build bf16 and int8 engines from ONE model)
    quantize_inplace: bool = False
    # contiguous-mode prefix store cap (blocks of materialized
    # per-layer K/V — real device memory on top of the engine's own
    # cache); None = a QUARTER engine's worth
    # (max_slots * max_len / page_size / 4), so the default can't
    # silently double an engine sized near HBM capacity. Paged mode
    # needs no cap: pool pressure evicts.
    prefix_cache_blocks: Optional[int] = None
    greedy: bool = True
    temperature: float = 1.0
    seed: int = 0
    # speculative decoding (PT_FLAGS_spec_decode): max draft tokens per
    # slot per verify pass — the verify program's fixed token width is
    # spec_k + 1 (drafts + the last accepted token), so this is a
    # compile-time shape, not a runtime knob
    spec_k: int = 4
    # crash recovery (PT_FLAGS_serve_recovery): how many times a
    # request may be re-queued for deterministic replay after a
    # quarantined step before it finishes with reason "failed";
    # add_request(max_retries=) overrides per request
    max_retries: int = 2


def _resolve_cache_dtype(requested):
    """EngineConfig.cache_dtype → concrete dtype. ``"auto"`` defers to
    the ``PT_FLAGS_kv_cache_dtype`` flag (auto = bfloat16 on TPU,
    float32 elsewhere — decode is KV-bandwidth-bound, so the cache
    dtype IS the decode traffic); explicit dtypes pass through.
    ``"int8"`` selects quantized KV pools (per-row f32 scales stored
    alongside; quantize-on-append, dequant in-kernel)."""
    named = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
             "float16": jnp.float16, "fp16": jnp.float16,
             "float32": jnp.float32, "fp32": jnp.float32,
             "int8": jnp.int8}

    def lookup(val, origin):
        if val not in named:
            raise ValueError(
                f"{origin} must be 'auto' or one of {sorted(named)}; "
                f"got {val!r}")
        return named[val]

    if isinstance(requested, str) and requested != "auto":
        return lookup(requested, "EngineConfig.cache_dtype")
    if requested not in (None, "auto"):
        return requested
    val = str(flags.flag("kv_cache_dtype")).lower()
    if val == "auto":
        return (jnp.bfloat16 if jax.default_backend() == "tpu"
                else jnp.float32)
    return lookup(val, "PT_FLAGS_kv_cache_dtype")


_WEIGHT_DTYPES = ("bf16", "int8", "int4")


def _resolve_weight_dtype(requested) -> str:
    """EngineConfig.weight_dtype → "bf16" | "int8" | "int4".
    ``"auto"`` defers to ``PT_FLAGS_serve_weight_dtype``; "bf16" means
    "serve the model's weights as they are" (no quantization pass)."""
    origin = "EngineConfig.weight_dtype"
    if requested in (None, "auto"):
        requested = flags.flag("serve_weight_dtype")
        origin = "PT_FLAGS_serve_weight_dtype"
    val = str(requested).lower()
    if val == "bfloat16":
        val = "bf16"
    if val not in _WEIGHT_DTYPES:
        raise ValueError(
            f"{origin} must be 'auto' or one of {list(_WEIGHT_DTYPES)}; "
            f"got {requested!r}")
    return val


def _validate_buckets(cfg: "EngineConfig") -> List[int]:
    """seq_buckets sanity at engine init: entries must be positive
    ints; the working table is normalized (sorted, deduped, clamped to
    max_len) so unsorted input can't break the bisect lookup and an
    oversized bucket can't over-allocate a one-shot prefill cache."""
    buckets = list(cfg.seq_buckets)
    if not buckets:
        raise ValueError("EngineConfig.seq_buckets must be non-empty")
    for b in buckets:
        if isinstance(b, bool) or not isinstance(b, (int, np.integer)) \
                or b <= 0:
            raise ValueError(
                f"EngineConfig.seq_buckets entries must be positive "
                f"ints; got {b!r}")
    return sorted({min(int(b), cfg.max_len) for b in buckets})


# per-request SLO classes (ROADMAP item 5): default TTFT / per-request
# TPOT targets per class; explicit add_request targets override. The
# engine only ACCOUNTS attainment here (pt_serve_slo_* counters,
# slo_snapshot, goodput) — the SLO-aware scheduler that acts on these
# classes is the next PR, and it reads exactly this bookkeeping.
SLO_CLASSES: Dict[str, Dict[str, float]] = {
    # deadline_ms is the class's default HARD deadline (enforced by
    # the scheduler: the request finishes with reason "timeout" and
    # its slot/pages/prefix refs are released), distinct from the
    # soft attainment targets above; add_request(deadline_ms=)
    # overrides, untracked requests default to no deadline
    "interactive": {"ttft_target_ms": 250.0, "tpot_target_ms": 100.0,
                    "deadline_ms": 30_000.0},
    "batch": {"ttft_target_ms": 5000.0, "tpot_target_ms": 1000.0,
              "deadline_ms": 300_000.0},
}


def new_slo_bucket() -> Dict[str, int]:
    """One per-class SLO accounting bucket. Engine- and fleet-level
    ``slo_stats`` share this shape (the router's ``slo_snapshot``
    merges replica buckets key-by-key), so a key added here reaches
    both sides at once."""
    return {
        "met": 0, "violated": 0, "cancelled": 0,
        "ttft_violations": 0, "tpot_violations": 0,
        "timeouts": 0, "met_tokens": 0, "total_tokens": 0,
    }


@dataclass
class Request:
    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    output: List[int] = field(default_factory=list)
    ttft_ms: Optional[float] = None
    slot: Optional[int] = None
    done: bool = False
    cancelled: bool = False
    # why the request left its slot: eos | max_new_tokens | max_len |
    # cancel | timeout | failed (None while in flight)
    finish_reason: Optional[str] = None
    # hard deadline: wall-clock budget from submission; the scheduler
    # expires the request (queued OR mid-decode) once it passes,
    # freeing slot/pages/prefix refs through the one teardown path
    deadline_ms: Optional[float] = None
    # per-request replay-retry bound (None = EngineConfig.max_retries)
    max_retries: Optional[int] = None
    # multi-tenant identity (None = the anonymous shared tenant "-"):
    # drives the SLO-fair scheduler's weighted fair share + quotas,
    # the per-tenant prefix-cache namespace, and the tenant label on
    # serve metrics — never the compiled programs (pure host policy)
    tenant: Optional[str] = None
    # SLO class + targets (None = untracked); tpot_ms is the
    # per-request mean decode latency, computed once at finish
    slo: Optional[str] = None
    ttft_target_ms: Optional[float] = None
    tpot_target_ms: Optional[float] = None
    tpot_ms: Optional[float] = None
    slo_met: Optional[bool] = None
    # per-request sampling params (None = engine-global config). Any
    # explicit temperature/top_k/top_p implies sampling for this
    # request; ``greedy`` overrides that inference either way.
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    greedy: Optional[bool] = None
    # attributed device cost (ms), accumulated step by step: each
    # step's measured program-ms (profiler sample; sync-wall estimate
    # on unsampled steps) split across the requests the step advanced,
    # proportional to tokens advanced. device_ms_profiled is the
    # portion backed by MEASURED samples (the rest is the honest
    # host-wall upper bound). Travels in request_ledger, so cost
    # survives failover/drain handoffs.
    device_ms: float = 0.0
    device_ms_profiled: float = 0.0
    _submit_t: float = 0.0
    _admit_t: float = 0.0
    # absolute deadline instant (perf_counter seconds; 0 = none)
    _deadline_t: float = 0.0
    # finish-time cost already recorded (idempotency guard: a request
    # can reach a terminal path more than once across flush points)
    _cost_recorded: bool = False
    # replay re-queues consumed so far (crash recovery)
    _retries: int = 0
    # prompt block digests, computed once — a pool-blocked request is
    # re-matched every scheduler tick and must not re-hash each time
    _hashes: Optional[List[bytes]] = None
    # speculative-decoding accounting (drives the auto-mode throttle
    # and the engine's acceptance stats)
    _spec_proposed: int = 0
    _spec_accepted: int = 0


def build_request(rid: int, prompt, max_new_tokens: int = 32,
                  eos_token_id: Optional[int] = None,
                  temperature: Optional[float] = None,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None,
                  greedy: Optional[bool] = None,
                  tenant: Optional[str] = None,
                  slo: Optional[str] = None,
                  ttft_target_ms: Optional[float] = None,
                  tpot_target_ms: Optional[float] = None,
                  deadline_ms: Optional[float] = None,
                  max_retries: Optional[int] = None,
                  *, max_len: int) -> Request:
    """Validate request arguments and construct a :class:`Request` —
    THE admission validation, factored out of ``add_request`` so the
    multi-engine router (``router.py``) applies the exact same checks
    when it builds a request before picking a replica. ``rid`` is the
    caller's: the engine passes its own counter, the router a
    fleet-unique one."""
    prompt = np.asarray(prompt).reshape(-1)
    if prompt.size == 0:
        # an empty prompt would "sample" from the last PADDED
        # position (last_idx = -1) — garbage logits, not a request
        raise ValueError("add_request needs a non-empty prompt")
    if prompt.size + max_new_tokens > max_len:
        raise ValueError(
            f"prompt({prompt.size}) + max_new_tokens({max_new_tokens}) "
            f"exceeds max_len={max_len}")
    if temperature is not None and temperature <= 0:
        raise ValueError(f"temperature must be > 0; got {temperature}")
    if top_k is not None and top_k < 0:
        raise ValueError(f"top_k must be >= 0; got {top_k}")
    if top_p is not None and not 0 < top_p <= 1:
        raise ValueError(f"top_p must be in (0, 1]; got {top_p}")
    if tenant is not None:
        if not isinstance(tenant, str) or not tenant \
                or len(tenant) > 64 \
                or any(c.isspace() or not c.isprintable()
                       for c in tenant):
            # the tenant string becomes a metric label, a prefix-cache
            # hash namespace and a scheduler dict key — reject shapes
            # that could mangle any of the three
            raise ValueError(
                "tenant must be a non-empty printable string without "
                f"whitespace, at most 64 chars; got {tenant!r}")
        if tenant == "-":
            raise ValueError(
                'tenant "-" is reserved for untagged requests')
    if slo is None and (ttft_target_ms is not None
                        or tpot_target_ms is not None):
        slo = "custom"  # explicit targets are an SLO by themselves
    if slo is not None and slo != "custom" and slo not in SLO_CLASSES:
        raise ValueError(
            f"slo must be one of {sorted(SLO_CLASSES)} (or custom "
            f"targets); got {slo!r}")
    if slo == "custom" and ttft_target_ms is None \
            and tpot_target_ms is None:
        # a targetless "custom" request would trivially count as
        # met every time — goodput inflation, not accounting
        raise ValueError(
            'slo="custom" needs ttft_target_ms and/or '
            "tpot_target_ms")
    for tname, t in (("ttft_target_ms", ttft_target_ms),
                     ("tpot_target_ms", tpot_target_ms)):
        if t is not None and t <= 0:
            raise ValueError(f"{tname} must be > 0; got {t}")
    defaults = SLO_CLASSES.get(slo, {})
    if slo is not None:
        if ttft_target_ms is None:
            ttft_target_ms = defaults.get("ttft_target_ms")
        if tpot_target_ms is None:
            tpot_target_ms = defaults.get("tpot_target_ms")
        if deadline_ms is None:
            deadline_ms = defaults.get("deadline_ms")
    if deadline_ms is not None:
        if deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0; got {deadline_ms}")
        if deadline_ms < 1.0:
            raise ValueError(
                f"deadline_ms={deadline_ms} is shorter than a "
                f"single scheduler step can honor (deadlines are "
                f"checked once per step; minimum 1 ms)")
    if max_retries is not None and (
            isinstance(max_retries, bool)
            or not isinstance(max_retries, (int, np.integer))
            or max_retries < 0):
        raise ValueError(
            f"max_retries must be a non-negative int; got "
            f"{max_retries!r}")
    req = Request(rid, prompt, max_new_tokens, eos_token_id,
                  temperature=temperature, top_k=top_k, top_p=top_p,
                  greedy=greedy, tenant=tenant, slo=slo,
                  ttft_target_ms=ttft_target_ms,
                  tpot_target_ms=tpot_target_ms,
                  deadline_ms=deadline_ms, max_retries=max_retries,
                  _submit_t=time.perf_counter())
    if deadline_ms is not None:
        req._deadline_t = req._submit_t + deadline_ms / 1e3
    return req


def request_namespace(req: Request) -> str:
    """The request's prefix-cache hash namespace: its tenant when
    tenant isolation is on (``PT_FLAGS_tenant_prefix_namespace``),
    else the shared default chain. ONE function for the engine's
    admission match and the router's affinity probe — the two must
    hash identically or affinity would steer traffic at pages the
    replica can never share."""
    if req.tenant and bool(flags.flag("tenant_prefix_namespace")):
        return req.tenant
    return ""


def request_ledger(req: Request) -> dict:
    """Serialize a request's HOST TOKEN LEDGER — the replay source of
    truth — into a plain dict another engine can re-admit via
    ``admit_ledger``: prompt + every generated token, sampling params,
    SLO targets and the ABSOLUTE deadline instant, plus the original
    submit/admit timestamps and TTFT so SLO accounting on the new
    engine stays the honest wall from FIRST submission. Timestamps are
    ``perf_counter`` values: the handoff contract is in-process (the
    router's replicas) or same-host."""
    return {
        "rid": int(req.rid),
        "prompt": [int(t) for t in req.prompt],
        "output": [int(t) for t in req.output],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token_id": req.eos_token_id,
        "temperature": req.temperature,
        "top_k": req.top_k,
        "top_p": req.top_p,
        "greedy": req.greedy,
        "tenant": req.tenant,
        "slo": req.slo,
        "ttft_target_ms": req.ttft_target_ms,
        "tpot_target_ms": req.tpot_target_ms,
        # absolute instant (perf_counter seconds; None = no deadline):
        # a handed-off request keeps its ORIGINAL budget — the move
        # must not grant it a fresh clock
        "deadline_t": req._deadline_t or None,
        "max_retries": req.max_retries,
        "retries": int(req._retries),
        "ttft_ms": req.ttft_ms,
        "submit_t": req._submit_t,
        "admit_t": req._admit_t,
        # attributed device cost so far: the move must not zero what
        # the request already burned (per-request cost accounting
        # survives failover/drain exactly like its SLO clock)
        "device_ms": float(req.device_ms),
        "device_ms_profiled": float(req.device_ms_profiled),
    }


class ContinuousBatchingEngine:
    """Slot-based continuous batching over a causal-LM Layer.

    The model must expose ``init_kv_caches`` and accept ``kv_caches`` /
    ``cache_index`` (vector per-slot lengths) in forward — the contract
    ``models/llama.py`` implements.
    """

    def __init__(self, model: Layer, config: Optional[EngineConfig] = None,
                 mesh=None, drafter: Optional[Drafter] = None,
                 fault_injector: Optional[FaultInjector] = None):
        """``drafter``: optional ``spec_decode.Drafter`` override for
        speculative decoding (default: ``NgramDrafter`` when
        ``PT_FLAGS_spec_decode`` is ``ngram``/``auto`` — the flag gates
        the path either way, so a custom drafter with the flag off is
        inert).

        ``fault_injector``: optional ``resilience.FaultInjector``
        override for chaos testing (default: built from
        ``PT_FLAGS_fault_inject``; None when the flag is empty).

        ``mesh``: optional ``jax.sharding.Mesh`` with a ``tp`` axis —
        tensor-parallel serving (parity: the reference's multi-GPU
        FastDeploy/fleet predictor). Params shard by their logical
        ``Parameter.spec`` (Column/RowParallelLinear carry tp specs);
        KV caches shard the kv-head axis; every compiled program runs
        under the mesh and GSPMD inserts the TP collectives. Requires
        num_key_value_heads divisible by the tp degree."""
        self.cfg = config or EngineConfig()
        cfg = self.cfg
        self.mesh = mesh

        # ---- quantized-serving config validation (at INIT, not at
        # first dispatch: a weight/cache dtype combination with no
        # kernel path must fail before any program compiles) ----
        self.weight_dtype = _resolve_weight_dtype(cfg.weight_dtype)
        self.cache_dtype = _resolve_cache_dtype(cfg.cache_dtype)
        if not isinstance(cfg.weight_group_size, (int, np.integer)) \
                or isinstance(cfg.weight_group_size, bool) \
                or cfg.weight_group_size < 1:
            raise ValueError(
                f"EngineConfig.weight_group_size must be a positive "
                f"int; got {cfg.weight_group_size!r}")
        if self.weight_dtype != "bf16" and mesh is not None:
            raise ValueError(
                f"weight_dtype={self.weight_dtype!r} has no "
                "tensor-parallel kernel path — quantized weight "
                "streaming is single-chip serving today (drop the "
                "mesh, or serve bf16 weights under it)")
        if self.cache_dtype == jnp.int8:
            if mesh is not None:
                raise ValueError(
                    "cache_dtype='int8' has no tensor-parallel kernel "
                    "path (scale pools are not mesh-sharded) — drop "
                    "the mesh or use a float cache dtype")
            if int(flags.flag("prefill_chunk")) <= 0:
                raise ValueError(
                    "cache_dtype='int8' requires the chunked prefill "
                    "path (PT_FLAGS_prefill_chunk > 0): the legacy "
                    "per-bucket prefill's one-shot insert programs "
                    "have no quantize-on-append path")

        # ---- weight-only quantization (the tentpole seam): replace
        # every linear with WeightOnlyLinear BEFORE param/buffer
        # extraction so the int8/int4 qweights + group scales become
        # buffers and ride every compiled program as jit arguments ----
        if self.weight_dtype != "bf16":
            import copy

            from ..quantization import quantize_model_weight_only

            if not cfg.quantize_inplace:
                model = copy.deepcopy(model)
            model = quantize_model_weight_only(
                model, weight_dtype=self.weight_dtype,
                group_size=cfg.weight_group_size)

        self.model = model
        model.eval()
        self.params = extract_params(model)
        # buffers (rope tables, int8/int4 qweights+scales after
        # quantize_model_weight_only) ride as ARGUMENTS, never as jit
        # constants — a 7B int8 model would otherwise bake ~7 GB of
        # weights into every compiled program
        self.buffers = extract_buffers(model)
        if self.weight_dtype != "bf16":
            # PTQ's act_scale calibration buffers are dead in every
            # weight-only serving forward (ptaudit DD001 found them
            # riding each compiled program as 15 unread args on the
            # tiny model alone) — drop them from the per-dispatch
            # buffer args; they stay on the Layer tree for
            # state_dict round-trips
            self.buffers = {n: v for n, v in self.buffers.items()
                            if not n.endswith(".act_scale")}
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..core.functional import extract_param_objs
            from ..distributed.sharding import model_shardings
            from ..distributed.strategy import DistributedStrategy

            if "tp" not in mesh.axis_names:
                raise ValueError(
                    f"tensor-parallel serving needs a mesh with a 'tp' "
                    f"axis; got axes {mesh.axis_names}")
            tp = mesh.shape["tp"]
            kvh = model.config.num_key_value_heads
            if kvh % tp:
                raise ValueError(
                    f"num_key_value_heads={kvh} not divisible by tp "
                    f"degree {tp} — KV caches shard the kv-head axis")
            strat = DistributedStrategy()  # logical specs only, no fsdp
            objs = extract_param_objs(model)
            shardings = model_shardings(model, mesh, strat,
                                        filter_to_mesh=True)
            self.params = {
                n: jax.device_put(v, shardings[n])
                for n, v in self.params.items()
            }
            # buffers replicate (rope tables; TP-sharded quantized
            # serving would thread specs here)
            repl = NamedSharding(mesh, P())
            self.buffers = {n: jax.device_put(v, repl)
                            for n, v in self.buffers.items()}
            # rebind the Layer tree to the placed arrays: keeping the
            # original single-device copies alive would hold the WHOLE
            # model on device 0 next to its 1/tp shard — an OOM exactly
            # when the model needs TP to fit
            for n, obj in objs.items():
                obj.value = self.params[n]
            owners = dict(model.named_sublayers(include_self=True))
            for n, v in self.buffers.items():
                mod_name, _, bname = n.rpartition(".")
                sub = owners.get(mod_name)
                if sub is not None and bname in sub._buffers:
                    sub._buffers[bname] = v
        self._pb = {"p": self.params, "b": self.buffers}

        self.seq_lens = np.zeros((cfg.max_slots,), np.int64)
        self.active = np.zeros((cfg.max_slots,), bool)
        self.last_tok = np.zeros((cfg.max_slots,), np.int64)
        # O(log slots) admission bookkeeping: a min-heap of free slots
        # (lowest index first, matching the old scan's choice) and a
        # sorted bucket table for bisect lookup — _admit_dispatch used
        # to rescan all slots twice and all buckets per queued request
        self._free_heap = list(range(cfg.max_slots))
        self._buckets = _validate_buckets(cfg)
        self._slot_req: Dict[int, Request] = {}
        self._queue: collections.deque = collections.deque()
        self._next_rid = 0
        # rid mint/advance is a read-modify-write shared between
        # producer-thread add_request callers and the scheduler's
        # handoff paths — unlocked, two producers could mint the
        # same rid and their finish records would collide
        self._rid_lock = threading.Lock()
        self._finished: Dict[int, Request] = {}
        self._key = jax.random.PRNGKey(cfg.seed)

        mcfg = model.config
        self._n_layers = mcfg.num_hidden_layers
        self._kvh = mcfg.num_key_value_heads
        self._hd = mcfg.head_dim
        if cfg.page_size < 1:
            # load-bearing in BOTH modes now: paged page granularity,
            # and the prefix-cache hash block length in contiguous mode
            raise ValueError(
                f"EngineConfig.page_size must be >= 1; got "
                f"{cfg.page_size}")
        if cfg.paged:
            if cfg.max_len % cfg.page_size:
                raise ValueError("max_len must be divisible by page_size")
            for bkt in cfg.seq_buckets:
                if min(bkt, cfg.max_len) % cfg.page_size:
                    raise ValueError(
                        f"seq bucket {bkt} not divisible by page_size="
                        f"{cfg.page_size} — prefill scatters whole pages")
        self._init_cache_state()

        self._decode_c = None
        self._decode_nc = None
        self._verify_c = None
        self._prefill_c = None
        self._insert_c = None
        self._scatter_c = None
        self._prefill_chunk_c = None
        self._insert_prefix_c = None
        self._read_block_c = None
        self._copy_page_c = None

        # single-program chunked prefill (PT_FLAGS_prefill_chunk): one
        # fixed [slots, C] chunk program in a host loop replaces the
        # per-bucket jit specializations; 0 = legacy bucketed prefill
        # floor of 2: a 1-token chunk would hit the models' s == 1
        # decode branch, whose append CLAMPS out-of-range positions —
        # the idle-slot start=max_len sentinel must always route
        # through the s > 1 scatter-with-drop path
        chunk = int(flags.flag("prefill_chunk"))
        self._chunk_len = max(2, min(chunk, cfg.max_len)) if chunk > 0 \
            else 0
        # prefix KV reuse (PT_FLAGS_prefix_cache) rides the chunked
        # path only: suffix-only prefill needs the vector-cache_index
        # chunk program, which the legacy bucketed oracle doesn't have
        self._prefix = None
        self._prefix_block = cfg.page_size
        if bool(flags.flag("prefix_cache")) and self._chunk_len:
            if cfg.paged:
                self._prefix = PagedPrefixStore()
            else:
                cap = cfg.prefix_cache_blocks
                if cap is None:
                    cap = max(cfg.max_slots * cfg.max_len
                              // max(self._prefix_block, 1) // 4, 1)
                self._prefix = ContigPrefixStore(cap)
        self.prefix_stats = {
            "hits": 0, "misses": 0, "hit_tokens": 0,
            "prompt_tokens": 0, "evictions": 0, "cow_copies": 0,
        }

        # speculative decoding (PT_FLAGS_spec_decode): host-side n-gram
        # drafting + ONE compiled [slots, spec_k+1] verify program.
        # "off" keeps this path entirely dark — today's decode trace,
        # bit for bit (the parity oracle the spec tests compare against)
        mode = str(flags.flag("spec_decode")).lower()
        if mode not in ("off", "ngram", "auto"):
            raise ValueError(
                f"PT_FLAGS_spec_decode must be off|ngram|auto; got "
                f"{mode!r}")
        if cfg.spec_k < 1:
            raise ValueError(
                f"EngineConfig.spec_k must be >= 1; got {cfg.spec_k}")
        self._spec_mode = mode
        self._drafter = None
        if mode != "off":
            self._drafter = drafter if drafter is not None \
                else NgramDrafter()
        self.spec_stats = {
            "proposed": 0, "accepted": 0, "emitted": 0,
            "verify_calls": 0, "fallback_steps": 0,
        }

        # SLO attainment bookkeeping (host counters — available even
        # with telemetry off, like prefix_stats/spec_stats): class ->
        # met/violated/target-miss/token counts, written at finish
        self.slo_stats: Dict[str, Dict[str, int]] = {}
        # ---- SLO-aware multi-tenant scheduler seam ----
        # optional host-side admission policy (serving_api.scheduler.
        # SLOFairScheduler is the shipped one; None = FIFO, today's
        # exact behavior). Pure policy: zero new compiled programs —
        # it only reorders which queued request claims a slot, caps
        # per-slot chunk budgets, and may preempt (see set_scheduler)
        self._sched = None
        self.sched_stats = {"policy": "fifo", "preemptions": 0}
        # tenant -> cumulative host counters (telemetry-off-safe,
        # like slo_stats); written at finish/preempt on the
        # scheduler thread, read via tenant_snapshot()
        self.tenant_stats: Dict[str, Dict[str, float]] = {}
        # set by the admission paths when the head request is blocked
        # on KV-pool pages (slots free, pool exhausted) — the PAGED
        # engine's dominant saturation mode, which a free-slot count
        # alone cannot see; read by backpressure()/healthz.
        # _pool_blocked_prev holds the PREVIOUS admission pass's
        # verdict (the live flag resets at each pass's start) — the
        # scheduler policy's preemption window reads it, because
        # "slots free but no pages" is exactly the saturation mode
        # where preempting a page-holding victim helps
        self._pool_blocked = False
        self._pool_blocked_prev = False

        # telemetry (None when PT_FLAGS_telemetry=off → scheduling loop
        # pays a single identity check per hook site)
        self._tel = (observability.ServingTelemetry()
                     if observability.enabled() else None)
        # lifecycle tracer (observability/tracing.py): same off-switch
        # as telemetry, thinned by PT_FLAGS_trace_sample; records
        # request spans + per-step composition into a bounded ring.
        # Pure host bookkeeping — adds zero compiled programs (pinned
        # by test_tracing's compile-count guard).
        self._tracer = None
        if self._tel is not None and float(flags.flag("trace_sample")) > 0:
            self._tracer = observability.Tracer(
                engine_id=self._tel.engine_id)

        # ---------------- resilience layer ----------------
        # seeded fault injector (PT_FLAGS_fault_inject; ctor override
        # for tests/benches) — None in production, zero overhead
        self._injector = (fault_injector if fault_injector is not None
                          else FaultInjector.from_flag())
        rec = str(flags.flag("serve_recovery")).lower()
        if rec not in ("auto", "all", "off"):
            raise ValueError(
                f"PT_FLAGS_serve_recovery must be auto|all|off; got "
                f"{rec!r}")
        self._recovery_mode = rec
        # graceful-degradation ladder (PT_FLAGS_degradation)
        self._degctl = (DegradationController()
                        if bool(flags.flag("degradation")) else None)
        # drain(): admission stopped, in-flight runs to completion
        self._draining = False
        # faults observed since the last health tick (feeds the ladder)
        self._faults_tick = 0
        # host counters (available with telemetry off, like spec_stats)
        self.resilience_stats = {
            "recoveries": 0, "retries": 0, "failed": 0, "timeouts": 0,
            "rebuilds": 0, "nan_steps": 0, "faults": {},
        }
        # lazy flight recorder for NaN-storm postmortem dumps (rides
        # PR 2's recorder: the dump attaches the tracer tail)
        self._recorder = None

        # ---------------- invariant sanitizer ----------------
        # PT_FLAGS_sanitize (analysis/sanitizer.py): per-tick state
        # invariants (page/refcount conservation, slot-heap +
        # block-table + scale-pool agreement, seq_len bounds vs the
        # host token ledger) and thread-ownership of scrape reads.
        # None when off — every hook site below pays a single identity
        # check, the telemetry=off pattern (pinned by test).
        self._san = None
        if bool(flags.flag("sanitize")):
            from ..analysis.sanitizer import EngineSanitizer

            self._san = EngineSanitizer(self)

        # ---------------- program profiler + recompile watchdog ------
        # PT_FLAGS_profile_programs (observability/profiling.py):
        # cadence-sampled block-until-ready timing around every
        # compiled dispatch — sampled dispatches record MEASURED
        # device ms (pt_serve_program_ms) + the schedule/dispatch/
        # device decomposition; unsampled dispatches stay fully async.
        # Off = None: one identity check per seam, zero new compiled
        # programs, outputs bit-identical (pinned by test).
        self._prof = None
        if bool(flags.flag("profile_programs")):
            self._prof = observability.ProgramProfiler(
                engine_id=(self._tel.engine_id
                           if self._tel is not None else None))
        # PT_FLAGS_recompile_watchdog: seal the expected program set
        # after warmup (tick budget, or engine.seal_programs()) and
        # count + flight-record any post-seal TRACE_COUNTS growth in
        # one of THIS engine's own ticks — the production complement
        # to ptlint TS003 and the test-only compile-count guards
        self._watchdog = None
        if bool(flags.flag("recompile_watchdog")):
            self._watchdog = observability.RecompileWatchdog(
                TRACE_COUNTS, TRACE_SHAPES,
                engine_id=(self._tel.engine_id
                           if self._tel is not None
                           else (self._prof.engine_id
                                 if self._prof is not None else "-")))
        # PT_FLAGS_audit_on_seal (analysis/program_audit.py): run the
        # jaxpr contract audit (AL/DQ/TX/DD rule families) over THIS
        # engine's own programs at its real shapes when the program
        # set seals — trace-only self-audit, no compile, no dispatch,
        # TRACE_COUNTS restored. Off (default) = one identity check
        # at seal; the verdict surfaces in metrics_snapshot()["audit"]
        self._audit_on_seal = bool(flags.flag("audit_on_seal"))
        self._audit_report = None
        # ---------------- flight data: history + alerts + cost -------
        # PT_FLAGS_timeseries (observability/timeseries.py): a bounded
        # ring of fixed-cadence windowed samples over this engine's
        # metrics, tick-driven (wall-clock-free in every decision) and
        # copy-on-read for the scrape thread. PT_FLAGS_alerts rides it:
        # rule-based detectors (SLO burn-rate, queue growth, hit-rate /
        # acceptance collapse, post-seal recompiles, HBM residency)
        # evaluate each closed window with hysteresis. Off = None —
        # one identity check per tick, zero new compiled programs,
        # outputs bit-identical (pinned by test).
        self._ts = None
        self._alerts = None
        if bool(flags.flag("timeseries")):
            label = (self._tel.engine_id if self._tel is not None
                     else None)
            self._ts = observability.TimeSeriesStore(label=label)
            if bool(flags.flag("alerts")):
                self._alerts = observability.AlertManager(
                    self._ts.label, tracer=self._tracer)
        # the degradation ladder's read-only burn-rate hook
        # (PT_FLAGS_slo_degradation, default off: the ladder's inputs
        # are untouched and its outputs pinned identical)
        self._slo_degradation = bool(flags.flag("slo_degradation"))
        # host tick/token counters the time-series collector windows
        # (cheap ints, always maintained — like prefix/spec stats)
        self._tokens_emitted = 0

        # per-request device-cost attribution (PT_FLAGS_cost_
        # attribution): split each step's measured program-ms
        # (profiler sample; sync-wall estimate on unsampled steps)
        # across the requests the step advanced, proportional to
        # tokens advanced. Pure host arithmetic over stamps the step
        # paths already take — zero device syncs, zero new compiled
        # programs; off = one identity check per seam.
        self._cost_enabled = bool(flags.flag("cost_attribution"))
        self.cost_stats = {
            # program -> total attributed ms (measured + estimated)
            "attributed_ms": {},
            # split by evidence: profiled_ms is backed by MEASURED
            # block-until-ready samples, estimated_ms by the honest
            # sync-wall upper bound on unsampled steps
            "profiled_ms": 0.0, "estimated_ms": 0.0,
            "requests_finished": 0,
            "request_device_ms_total": 0.0,
            # slo class (or "untracked") -> {requests, device_ms_total}
            "by_slo": {},
        }
        # recent finished-request costs (p50 over the window)
        self._cost_window: collections.deque = collections.deque(
            maxlen=512)
        # requests that reached a terminal state mid-step: their
        # finish-time cost recording is deferred past the step's
        # attribution pass (the final chunk's share must be included)
        self._cost_pending: List[Request] = []

        # live HBM residency gauges (host metadata only): the weight
        # components are immutable after init — computed ONCE here so
        # profiler-sampled refreshes only re-walk the (small) dynamic
        # parts; baseline the gauges now that the pools exist
        self._hbm_weights = observability.profiling \
            .weight_bytes_by_dtype(self.params, self.buffers)
        self._hbm_update()

    def _init_cache_state(self):
        """(Re)build the KV-cache device arrays and the page-pool
        bookkeeping — called at init and by hard crash recovery
        (``_rebuild_caches``). Shapes are identical across rebuilds,
        so the jitted programs never re-specialize (pinned by the
        recovery compile-count guard)."""
        cfg = self.cfg
        if cfg.paged:
            max_pages_per_slot = cfg.max_len // cfg.page_size
            # +1: page 0 is the inactive-slot write sink, never allocated
            n_pages = cfg.n_pages or \
                cfg.max_slots * max_pages_per_slot + 1
            self.pool = PagePool(n_pages, cfg.page_size, cfg.max_slots,
                                 max_pages_per_slot, reserve_sink=True)
            self.layer_caches = init_paged_pool(
                self._n_layers, n_pages, cfg.page_size, self._kvh,
                self._hd, dtype=self.cache_dtype)
            if self.mesh is not None:
                self.layer_caches = [
                    PagedLayerCache(self._shard_kv(c.k_pages, axis=0),
                                    self._shard_kv(c.v_pages, axis=0))
                    for c in self.layer_caches]
        else:
            self.pool = None
            self.caches = self.model.init_kv_caches(
                cfg.max_slots, cfg.max_len, dtype=self.cache_dtype)
            if self.mesh is not None:
                self.caches = [
                    (self._shard_kv(k), self._shard_kv(v))
                    for k, v in self.caches]

    def _shard_kv(self, arr, axis=-2):
        """Shard the kv-head axis over tp (requires kv_heads % tp == 0):
        axis -2 for contiguous [..., kv_heads, head_dim] caches, axis 0
        for the head-major paged pool."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = [None] * arr.ndim
        spec[axis] = "tp"
        return jax.device_put(arr, NamedSharding(self.mesh, P(*spec)))

    def _ctx(self):
        import contextlib

        if self.mesh is None:
            return contextlib.nullcontext()
        from ..distributed.sharding import mesh_context

        return mesh_context(self.mesh)

    # ---------------- scheduler policy seam ----------------
    def set_scheduler(self, policy):
        """Install (or clear, with ``None``) the admission scheduler
        policy — the SLO-aware multi-tenant scheduler's seam into the
        engine. The policy is consulted on the SCHEDULER THREAD only,
        at three points:

        * ``pick(engine, candidates)`` — admission ORDER: choose the
          next queued request to claim a slot (replaces FIFO).
        * ``before_admission(engine)`` — the preemption window before
          each admission wave; may call ``engine.preempt(slot)`` and
          returns the preempted rids (excluded from this wave).
        * ``slot_caps(engine)`` — per-slot decode-token caps applied
          to each chunk's budget vector (``None`` = uncapped).
        * ``note_admit(engine, req)`` — fair-share accounting hook,
          called when a pick's claim commits.

        Pure host-side policy: the compiled program set is untouched
        (pinned by the compile-counter guards) and greedy outputs are
        per-request bit-identical under any admission order. Policy
        rides the CHUNKED admission path only — the legacy bucketed
        prefill (``PT_FLAGS_prefill_chunk=0``) stays FIFO, like the
        prefix cache."""
        self._sched = policy
        self.sched_stats["policy"] = (
            "fifo" if policy is None
            else getattr(policy, "name", type(policy).__name__))

    def _pick_admission(self, skip, fifo_cursor):
        """Admission-order seam: the next queued request to TRY (a
        peek — removal happens only when its slot/page claim commits),
        or None to stop this wave. ``skip`` holds rids already
        deferred OR committed this wave (shed batch / draining /
        preempted / claimed). Default FIFO rides ``fifo_cursor`` — a
        wave-local ``[snapshot, index]`` pair, ONE queue copy per
        wave with a monotone index (a deep shed/drain wave must stay
        O(queue), not O(queue²)). With a policy: the policy re-ranks
        a fresh snapshot per pick (usage/urgency move as the wave
        claims slots)."""
        if self._sched is None:
            if not skip:
                # pure-FIFO fast path: head peek, O(1) — the
                # snapshot is not even taken until something defers
                return self._queue[0] if self._queue else None
            cands, i = fifo_cursor
            if cands is None:
                cands = fifo_cursor[0] = list(self._queue)
            while i < len(cands) and cands[i].rid in skip:
                i += 1
            fifo_cursor[1] = i
            return cands[i] if i < len(cands) else None
        cands = [r for r in list(self._queue) if r.rid not in skip]
        if not cands:
            return None
        return self._sched.pick(self, cands)

    def preempt(self, slot: int) -> bool:
        """Preempt the ACTIVE request in ``slot``: release its
        slot/KV pages/prefix refs through the one teardown path and
        re-queue it at the FRONT with its generated history intact.
        Re-admission replays prompt+history through the existing
        ``[slots, C]`` chunked prefill program — the crash-recovery
        path — so greedy outputs stay bit-identical and ZERO new
        programs compile. TTFT/admit instants and attributed cost are
        preserved (the request is the same object); the price is the
        replay's prefill recompute, which the scheduler policy must
        weigh (and bound) before calling.

        Scheduler-thread only, same contract as ``cancel``: an
        in-flight chunk's writes to the freed pages are stream-ordered
        before any successor's prefill writes, and the host loop
        discards the preempted slot's remaining chunk tokens via the
        ``active`` mask."""
        req = self._slot_req.get(slot)
        if req is None:
            return False
        self._release_slot(slot)
        req.slot = None
        # replay ids grow by the generated history: stale digests
        # (hashed at admission) no longer cover them
        req._hashes = None
        self._queue.appendleft(req)
        self.sched_stats["preemptions"] += 1
        self._tenant_bucket(req.tenant)["preemptions"] += 1
        if self._tel is not None:
            self._tel.on_preempt()
        tr = self._tracer
        if tr is not None and tr.want_request(req.rid):
            tr.request(req.rid, "preempt", slot=slot,
                       tokens=len(req.output),
                       tenant=req.tenant or "-")
        return True

    # ---------------- request lifecycle ----------------
    def add_request(self, prompt, max_new_tokens: int = 32,
                    eos_token_id: Optional[int] = None,
                    temperature: Optional[float] = None,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    greedy: Optional[bool] = None,
                    tenant: Optional[str] = None,
                    slo: Optional[str] = None,
                    ttft_target_ms: Optional[float] = None,
                    tpot_target_ms: Optional[float] = None,
                    deadline_ms: Optional[float] = None,
                    max_retries: Optional[int] = None) -> int:
        """``temperature``/``top_k``/``top_p``: per-request sampling
        params, routed through ``generation.process_logits_batch``
        IN-JIT as per-slot vectors — setting any of them makes this
        request sample (``greedy=True`` overrides back to argmax;
        leaving all four ``None`` keeps the engine-global
        ``EngineConfig.greedy``/``temperature`` behavior and its exact
        compiled trace). Sampling requests never draft for speculative
        decoding — greedy acceptance needs an argmax chain to verify
        against.

        ``tenant``: multi-tenant identity (non-empty printable
        string, no whitespace, ≤64 chars; ``None`` = untagged). Drives
        the SLO-fair scheduler's weighted fair share and quotas, the
        per-tenant prefix-cache namespace
        (``PT_FLAGS_tenant_prefix_namespace``) and the tenant label on
        serve metrics — never the compiled programs.

        ``slo``: latency class (``"interactive"`` | ``"batch"``) whose
        TTFT / per-request-TPOT targets (``SLO_CLASSES``, overridable
        via ``ttft_target_ms``/``tpot_target_ms``; explicit targets
        alone imply class ``"custom"``) are checked at finish —
        attainment lands in ``pt_serve_slo_{met,violated}_total``, the
        goodput gauge and ``engine.slo_snapshot()``. ``None`` leaves
        the request SLO-untracked.

        ``deadline_ms``: hard wall-clock budget from submission — the
        scheduler expires the request (queued or mid-decode) once it
        passes, finishing it with ``finish_reason="timeout"`` and
        provably freeing its slot, KV pages and prefix refs. Defaults
        to the SLO class's ``deadline_ms`` when ``slo`` is set, else
        no deadline. Must be >= 1 ms: the scheduler checks deadlines
        once per step, so a sub-millisecond deadline is shorter than a
        single step can honor and would expire unconditionally.

        ``max_retries``: per-request bound on crash-recovery replay
        re-queues (default ``EngineConfig.max_retries``); past it the
        request finishes with ``finish_reason="failed"``."""
        req = build_request(
            0, prompt, max_new_tokens, eos_token_id,
            temperature=temperature, top_k=top_k, top_p=top_p,
            greedy=greedy, tenant=tenant, slo=slo,
            ttft_target_ms=ttft_target_ms,
            tpot_target_ms=tpot_target_ms, deadline_ms=deadline_ms,
            max_retries=max_retries, max_len=self.cfg.max_len)
        # mint AFTER validation (a rejected request burns no rid) and
        # under the lock: concurrent producer threads reading the
        # counter before either advanced it would share a rid
        with self._rid_lock:
            req.rid = self._next_rid
            self._next_rid += 1
        return self.submit_request(req)

    def submit_request(self, req: Request) -> int:
        """Enqueue an externally built, NEVER-RUN :class:`Request`
        directly — the router's first-placement fast path (the caller
        owns the rid space and already validated via
        ``build_request``). Requests carrying history (failover
        replay, drain handoff) move between engines via
        ``admit_ledger`` instead, which rebuilds state from the token
        ledger."""
        with self._rid_lock:
            self._next_rid = max(self._next_rid, req.rid + 1)
        self._queue.append(req)
        if self._tel is not None:
            self._tel.on_submit(len(self._queue))
        tr = self._tracer
        if tr is not None and tr.want_request(req.rid):
            tr.request(req.rid, "queued", t0=req._submit_t,
                       prompt_tokens=int(req.prompt.size),
                       max_new_tokens=int(req.max_new_tokens),
                       slo=req.slo or "")
        return req.rid

    def admit_ledger(self, ledger: dict) -> int:
        """Re-admit a request handed off from ANOTHER engine — the
        receiving half of the handoff API (``drain()['unfinished']`` /
        the router's cross-replica failover). The ledger's generated
        tokens are host-side truth, so admission replays
        prompt+history through the existing ``[slots, C]`` chunked
        prefill program (``_prefill_ids``) and greedy decoding
        continues bit-identically; the ORIGINAL submit/admit instants,
        TTFT and absolute deadline carry over, so SLO accounting never
        resets across the move. The caller owns the rid space
        (fleet-unique rids) — a rid this engine already knows is
        rejected, the dual-ownership the fleet sanitizer forbids."""
        rid = int(ledger["rid"])
        known = rid in self._finished
        if not known:
            try:
                known = any(
                    r.rid == rid for r in list(self._queue)) \
                    or any(r.rid == rid
                           for r in list(self._slot_req.values()))
            except RuntimeError:
                # a producer-thread handoff racing the scheduler's own
                # structure mutation: the uniqueness guard is
                # best-effort off-thread — true dual ownership is
                # still caught by the fleet sanitizer at the next tick
                known = False
        if known:
            raise ValueError(
                f"admit_ledger: rid {rid} is already owned by this "
                "engine (queued, active, or finished) — a handoff "
                "must MOVE a request, never copy it")
        req = build_request(
            rid, np.asarray(ledger["prompt"], np.int64),
            int(ledger["max_new_tokens"]), ledger.get("eos_token_id"),
            temperature=ledger.get("temperature"),
            top_k=ledger.get("top_k"), top_p=ledger.get("top_p"),
            greedy=ledger.get("greedy"), tenant=ledger.get("tenant"),
            slo=ledger.get("slo"),
            ttft_target_ms=ledger.get("ttft_target_ms"),
            tpot_target_ms=ledger.get("tpot_target_ms"),
            max_retries=ledger.get("max_retries"),
            max_len=self.cfg.max_len)
        req.output = [int(t) for t in ledger.get("output", ())]
        req.ttft_ms = ledger.get("ttft_ms")
        req._retries = int(ledger.get("retries", 0))
        req.device_ms = float(ledger.get("device_ms", 0.0) or 0.0)
        req.device_ms_profiled = float(
            ledger.get("device_ms_profiled", 0.0) or 0.0)
        # original instants win over build_request's fresh stamps: the
        # move must not shrink queue-wait out of TTFT or grant a fresh
        # deadline clock
        if ledger.get("submit_t"):
            req._submit_t = float(ledger["submit_t"])
        if ledger.get("admit_t"):
            req._admit_t = float(ledger["admit_t"])
        req._deadline_t = float(ledger.get("deadline_t") or 0.0)
        # keep the local counter ahead of adopted rids so standalone
        # add_request on this engine can never collide with a handoff
        with self._rid_lock:
            self._next_rid = max(self._next_rid, rid + 1)
        self._queue.append(req)
        if self._tel is not None:
            self._tel.on_submit(len(self._queue))
        tr = self._tracer
        if tr is not None and tr.want_request(rid):
            tr.request(rid, "queued", t0=req._submit_t,
                       prompt_tokens=int(req.prompt.size),
                       max_new_tokens=int(req.max_new_tokens),
                       slo=req.slo or "", handoff=True,
                       replayed_tokens=len(req.output))
        return rid

    def _req_greedy(self, req: Request) -> bool:
        if req.greedy is not None:
            return req.greedy
        if (req.temperature is not None or req.top_k is not None
                or req.top_p is not None):
            return False  # explicit sampling params imply sampling
        return self.cfg.greedy

    def _req_nondefault(self, req: Request) -> bool:
        """True when the request's EFFECTIVE next-token selection
        differs from the engine-global config — only then must the
        compiled programs take the per-slot sampling arm (and pay its
        vocab sort). Merely *passing* an override that lands on the
        default (``greedy=True`` on a greedy engine, ``top_k=0``,
        ``top_p=1.0``, the engine's own temperature) keeps the plain
        arm and its exact trace."""
        g = self._req_greedy(req)
        if g != bool(self.cfg.greedy):
            return True
        if g:
            return False  # argmax is argmax; temp/top-k/top-p unused
        return ((req.temperature is not None
                 and req.temperature != self.cfg.temperature)
                or bool(req.top_k)
                or (req.top_p is not None and req.top_p < 1.0))

    def _slot_sampling(self, reqs=None):
        """(use_samp, per-slot param vectors) for the compiled
        programs. ``use_samp`` is False when every live request rides
        the engine-global config — the programs' static no-sampling arm
        then reproduces the pre-per-request-params trace exactly (and
        never pays the vocab sort). ``reqs``: optional explicit
        (slot, Request) pairs (a prefill wave); defaults to the active
        slot map."""
        cfg = self.cfg
        items = list(self._slot_req.items()) if reqs is None else reqs
        greedy = np.full((cfg.max_slots,), bool(cfg.greedy))
        temp = np.full((cfg.max_slots,), max(cfg.temperature, 1e-6),
                       np.float32)
        tk = np.zeros((cfg.max_slots,), np.int32)
        tp = np.ones((cfg.max_slots,), np.float32)
        use = False
        for slot, req in items:
            use = use or self._req_nondefault(req)
            greedy[slot] = self._req_greedy(req)
            if req.temperature is not None:
                temp[slot] = max(req.temperature, 1e-6)
            if req.top_k is not None:
                tk[slot] = req.top_k
            if req.top_p is not None:
                tp[slot] = req.top_p
        samp = (jnp.asarray(greedy), jnp.asarray(temp),
                jnp.asarray(tk), jnp.asarray(tp))
        return use, samp

    def _sample_rows(self, rows, key, samp, use_samp):
        """Next-token selection over ``[slots, vocab]`` rows inside the
        compiled programs. The static ``use_samp`` arm routes per-slot
        params through ``generation.process_logits_batch`` (greedy
        slots keep pure argmax — a sampling neighbor can't perturb
        them); the other arm is the engine-global config, compiled
        exactly as before per-request params existed."""
        if use_samp:
            greedy_mask, temp, tk, tp = samp
            g = jnp.argmax(rows, axis=-1)
            s = jax.random.categorical(
                key, G.process_logits_batch(rows, temp, tk, tp), axis=-1)
            return jnp.where(greedy_mask, g, s)
        if self.cfg.greedy:
            return jnp.argmax(rows, axis=-1)
        return jax.random.categorical(
            key, rows / self.cfg.temperature, axis=-1)

    def _free_slots(self) -> List[int]:
        return sorted(self._free_heap)

    # ---------------- compiled programs ----------------
    def _bucket(self, n: int) -> int:
        i = bisect.bisect_left(self._buckets, n)
        return self._buckets[i] if i < len(self._buckets) \
            else self.cfg.max_len

    def _prefill(self):
        # one jitted fn serves every bucket: jit specializes per shape.
        # Samples the first token IN-JIT so only a scalar crosses to the
        # host — never the [1, bucket, vocab] logits tensor.
        if self._prefill_c is None:
            def fn(pb, ids, caches, last_idx, key, samp, use_samp):
                TRACE_COUNTS["prefill_bucket"] += 1
                _shape_note("prefill_bucket", ids=ids)
                pos = jnp.broadcast_to(
                    jnp.arange(ids.shape[1])[None, :], ids.shape)
                logits, filled = functional_call(
                    self.model, pb["p"], ids, position_ids=pos,
                    kv_caches=caches, cache_index=0, buffers=pb["b"])
                last = logits[0, last_idx]
                if use_samp:
                    # single-request program: samp carries [1] vectors
                    first = self._sample_rows(last[None], key, samp,
                                              True)[0]
                elif self.cfg.greedy:
                    first = jnp.argmax(last)
                else:
                    first = jax.random.categorical(
                        key, last / self.cfg.temperature)
                return first, filled
            # caches (the fresh per-call bucket cache) is donated: the
            # program fills it in place and the caller only ever uses
            # the returned `filled`. ptaudit AL001 found the missing
            # donation — without it every legacy prefill paid a full
            # bucket-cache copy on top of the fill
            self._prefill_c = jax.jit(fn, static_argnums=(6,),
                                      donate_argnums=(2,))
        return self._prefill_c

    def _insert_contig(self):
        # write a single-sequence prefill cache into slot `slot` of the
        # global contiguous cache (dynamic_update_slice over slot axis)
        if self._insert_c is None:
            def fn(global_caches, one_caches, slot):
                TRACE_COUNTS["prefill_insert"] += 1
                _shape_note("prefill_insert", one_k=one_caches[0][0])
                out = []
                for (gk, gv), (ok, ov) in zip(global_caches, one_caches):
                    pad = gk.shape[1] - ok.shape[1]
                    ok = jnp.pad(ok, ((0, 0), (0, pad), (0, 0), (0, 0)))
                    ov = jnp.pad(ov, ((0, 0), (0, pad), (0, 0), (0, 0)))
                    gk = jax.lax.dynamic_update_slice_in_dim(
                        gk, ok.astype(gk.dtype), slot, 0)
                    gv = jax.lax.dynamic_update_slice_in_dim(
                        gv, ov.astype(gv.dtype), slot, 0)
                    out.append((gk, gv))
                return out
            self._insert_c = jax.jit(fn, donate_argnums=(0,))
        return self._insert_c

    def _scatter_paged(self):
        # scatter a [1, bucket] prefill cache into this slot's pages;
        # bucket/n_used come from the traced shapes, so one jitted fn
        # specializes per bucket automatically
        if self._scatter_c is None:
            ps = self.cfg.page_size

            def fn(layer_caches, one_caches, bt_row):
                TRACE_COUNTS["prefill_scatter"] += 1
                _shape_note("prefill_scatter", one_k=one_caches[0][0], bt_row=bt_row)
                out = []
                for cache, (ok, ov) in zip(layer_caches, one_caches):
                    n_used = ok.shape[1] // ps
                    pages = bt_row[:n_used]
                    # [1, bucket, kvh, d] -> head-major [kvh, n_used, ps, d]
                    okp = ok[0].reshape(n_used, ps, *ok.shape[2:]) \
                        .transpose(2, 0, 1, 3)
                    ovp = ov[0].reshape(n_used, ps, *ov.shape[2:]) \
                        .transpose(2, 0, 1, 3)
                    # _replace (not positional rebuild): this legacy
                    # path never serves int8 pools (rejected at init),
                    # but a positional ctor would silently DROP scale
                    # arrays if that ever changed
                    out.append(cache._replace(
                        k_pages=cache.k_pages.at[:, pages].set(
                            okp.astype(cache.k_pages.dtype)),
                        v_pages=cache.v_pages.at[:, pages].set(
                            ovp.astype(cache.v_pages.dtype)),
                    ))
                return out
            self._scatter_c = jax.jit(fn, donate_argnums=(0,))
        return self._scatter_c

    def _prefill_chunked(self):
        """THE prefill program: one fixed-shape [slots, C] chunk,
        writing straight into the live global cache at per-slot
        offsets. A host loop drives chunk k over suffix tokens
        [k·C, (k+1)·C); slots not prefilling this call carry a
        ``start = max_len`` sentinel (their writes drop, their outputs
        are ignored). Samples a first token per slot in-jit from the
        per-slot ``last_idx`` row — only scalars ever cross to the
        host; the host uses the sample from each request's final chunk.
        One jit specialization serves EVERY prompt length (the compile
        count the trace guard asserts), and multiple queued requests'
        chunks pack into the same call. The shape is [slots, C] like
        the decode program's [slots, 1]: a lone admission still
        computes every slot's rows (sentinels included) — the win is
        per-REQUEST marginal cost under packing, not the cost of an
        unpacked call."""
        if self._prefill_chunk_c is None:
            paged = self.cfg.paged
            C = self._chunk_len

            def fn(pb, ids, caches, bt, start, last_idx, key, samp,
                   use_samp):
                TRACE_COUNTS["prefill_chunk"] += 1
                _shape_note("prefill_chunk", ids=ids, start=start)
                pos = start[:, None] + jnp.arange(C, dtype=jnp.int32)
                if paged:
                    state = PagedState(block_tables=bt, seq_lens=start)
                    kv = [(c, state) for c in caches]
                else:
                    kv = caches
                logits, new_kv = functional_call(
                    self.model, pb["p"], ids, position_ids=pos,
                    kv_caches=kv, cache_index=start, buffers=pb["b"])
                rows = logits[jnp.arange(logits.shape[0]), last_idx]
                toks = self._sample_rows(rows, key, samp, use_samp)
                if paged:
                    return toks, [c for c, _ in new_kv]
                return toks, new_kv
            self._prefill_chunk_c = jax.jit(fn, static_argnums=(8,),
                                            donate_argnums=(2,))
        return self._prefill_chunk_c

    def _insert_prefix_contig(self):
        """Write one cached prefix block (k/v stacked over layers,
        [n_layers, B, kvh, d]) into a slot's contiguous cache rows at
        ``start`` — the contiguous-mode prefix 'share' is a copy.
        One dispatch per matched block (a variable-count batched write
        would re-specialize per hit length); fine for the contiguous
        mode's scale — production paged serving shares pages with zero
        copies instead."""
        if self._insert_prefix_c is None:
            from .paged import QuantizedKV

            def ins(g, blk, i, slot, start):
                if isinstance(g, QuantizedKV):
                    # int8 caches: the stored block carries its scale
                    # rows — payload and scales insert together
                    return QuantizedKV(
                        jax.lax.dynamic_update_slice(
                            g.q, blk.q[i][None].astype(g.q.dtype),
                            (slot, start, 0, 0)),
                        jax.lax.dynamic_update_slice(
                            g.scale, blk.scale[i][None],
                            (slot, start, 0)))
                return jax.lax.dynamic_update_slice(
                    g, blk[i][None].astype(g.dtype), (slot, start, 0, 0))

            def fn(global_caches, kblk, vblk, slot, start):
                TRACE_COUNTS["prefix_insert"] += 1
                _shape_note("prefix_insert", kblk=kblk, vblk=vblk)
                out = []
                for i, (gk, gv) in enumerate(global_caches):
                    out.append((ins(gk, kblk, i, slot, start),
                                ins(gv, vblk, i, slot, start)))
                return out
            self._insert_prefix_c = jax.jit(fn, donate_argnums=(0,))
        return self._insert_prefix_c

    def _read_block_contig(self):
        """Slice one block of a slot's rows out of every layer's
        contiguous cache, stacked [n_layers, B, kvh, d] — the store's
        materialized copy of a fresh prefix block."""
        if self._read_block_c is None:
            B = self._prefix_block
            from .paged import QuantizedKV

            def rd(g, slot, start):
                if isinstance(g, QuantizedKV):
                    qsz = (1, B) + g.q.shape[2:]
                    ssz = (1, B) + g.scale.shape[2:]
                    return QuantizedKV(
                        jax.lax.dynamic_slice(
                            g.q, (slot, start, 0, 0), qsz)[0],
                        jax.lax.dynamic_slice(
                            g.scale, (slot, start, 0), ssz)[0])
                sz = (1, B) + g.shape[2:]
                return jax.lax.dynamic_slice(
                    g, (slot, start, 0, 0), sz)[0]

            def stack(blks):
                if isinstance(blks[0], QuantizedKV):
                    # the store's block keeps its scale rows: dequant
                    # state survives insert into a future slot
                    return QuantizedKV(
                        jnp.stack([b.q for b in blks]),
                        jnp.stack([b.scale for b in blks]))
                return jnp.stack(blks)

            def fn(global_caches, slot, start):
                TRACE_COUNTS["prefix_read"] += 1
                _shape_note("prefix_read", k0=global_caches[0][0])
                ks, vs = [], []
                for gk, gv in global_caches:
                    ks.append(rd(gk, slot, start))
                    vs.append(rd(gv, slot, start))
                return stack(ks), stack(vs)
            self._read_block_c = jax.jit(fn)
        return self._read_block_c

    def _copy_page(self):
        """Copy-on-write device copy: duplicate page ``src`` into
        ``dst`` across every layer's pool (src/dst are traced scalars —
        one specialization ever)."""
        if self._copy_page_c is None:
            def copy1(arr, src, dst):
                return jax.lax.dynamic_update_slice_in_dim(
                    arr,
                    jax.lax.dynamic_slice_in_dim(arr, src, 1, axis=1),
                    dst, axis=1)

            def fn(layer_caches, src, dst):
                TRACE_COUNTS["page_copy"] += 1
                _shape_note("page_copy", k_pages=layer_caches[0].k_pages)
                out = []
                for c in layer_caches:
                    rep = {"k_pages": copy1(c.k_pages, src, dst),
                           "v_pages": copy1(c.v_pages, src, dst)}
                    if c.k_scale is not None:
                        # int8 pools: a COW'd page keeps its dequant
                        # state — the scale rows copy with the page
                        rep["k_scale"] = copy1(c.k_scale, src, dst)
                        rep["v_scale"] = copy1(c.v_scale, src, dst)
                    out.append(c._replace(**rep))
                return out
            self._copy_page_c = jax.jit(fn, donate_argnums=(0,))
        return self._copy_page_c

    def _decode(self):
        if self._decode_c is None:
            paged = self.cfg.paged

            def fn(pb, toks, caches, state_or_lens, key, samp, use_samp):
                # only `caches` (arg 2) is donated; the per-slot lengths /
                # block tables must NOT alias it (f(donate(a), a) trap)
                TRACE_COUNTS["decode_step"] += 1
                _shape_note("decode_step", toks=toks)
                if paged:
                    state = state_or_lens
                    seq_lens = state.seq_lens
                    kv = [(c, state) for c in caches]
                else:
                    seq_lens = state_or_lens
                    kv = caches
                pos = seq_lens[:, None]
                logits, new_kv = functional_call(
                    self.model, pb["p"], toks, position_ids=pos,
                    kv_caches=kv, cache_index=seq_lens, buffers=pb["b"])
                logits = logits[:, -1, :]
                nxt = self._sample_rows(logits, key, samp, use_samp)
                if paged:
                    new_caches = [c for c, _ in new_kv]
                    return nxt, new_caches
                return nxt, new_kv
            self._decode_c = jax.jit(fn, static_argnums=(6,),
                                     donate_argnums=(2,))
        return self._decode_c

    def _decode_n(self):
        """K decode steps fused into one device program (lax.scan): the
        sampled token feeds the next step ON DEVICE; the host syncs once
        per K tokens instead of per token. K is FIXED at
        ``cfg.decode_chunk``-or-caller's max_chunk so exactly one program
        ever compiles; per-slot ``budget`` (a traced vector) freezes a
        slot once it has produced its remaining tokens — its length stops
        advancing, so overflow steps rewrite the same in-allocation cache
        position with discarded garbage. Inactive slots likewise never
        advance (their writes land in the slot's own row / the paged sink
        page, both overwritten or freed at admission)."""
        if self._decode_nc is None:
            paged = self.cfg.paged

            def fn(pb, toks, caches, lens, active, budget, bt, key, samp,
                   K, use_samp):
                TRACE_COUNTS["decode_chunk"] += 1
                _shape_note("decode_chunk", toks=toks, budget=budget)

                def one(carry, k):
                    toks, caches, lens = carry
                    if paged:
                        state = PagedState(block_tables=bt, seq_lens=lens)
                        kv = [(c, state) for c in caches]
                    else:
                        kv = caches
                    logits, new_kv = functional_call(
                        self.model, pb["p"], toks,
                        position_ids=lens[:, None],
                        kv_caches=kv, cache_index=lens, buffers=pb["b"])
                    logits = logits[:, -1, :]
                    nxt = self._sample_rows(
                        logits, jax.random.fold_in(key, k), samp,
                        use_samp)
                    nxt = nxt.astype(toks.dtype)
                    if paged:
                        new_caches = [c for c, _ in new_kv]
                    else:
                        new_caches = new_kv
                    advance = active & (k < budget)
                    new_lens = lens + advance.astype(lens.dtype)
                    new_toks = jnp.where(advance[:, None], nxt[:, None],
                                         toks)
                    return (new_toks, new_caches, new_lens), nxt

                (toks, caches, lens), toks_all = jax.lax.scan(
                    one, (toks, caches, lens), jnp.arange(K))
                return toks_all, caches, lens

            self._decode_nc = jax.jit(
                fn, static_argnums=(9, 10), donate_argnums=(2,))
        return self._decode_nc

    def _verify(self):
        """THE speculative-decoding program: one compiled fixed
        ``[slots, spec_k+1]`` target-model pass that scores each slot's
        last accepted token plus up to K drafted tokens, with GREEDY
        ACCEPTANCE computed in-jit — only ``[slots]``-sized preds and
        accepted-lengths cross to the host, never logits.

        Same shape discipline as the chunked prefill program (it rides
        the models' identical per-slot s>1 branches: vector
        ``cache_index``, scatter-with-drop appends, per-row causal
        history mask): slots with no draft this step carry
        ``n_draft = 0`` and degrade to a normal one-token decode within
        the same program — row 0's prediction IS the decode token;
        inactive slots carry the ``start = max_len`` write-drop
        sentinel. Every row's K/V is appended to the cache (pad rows
        write garbage PAST the slot's live length); the host then
        advances ``seq_lens`` by only ``accepted+1``, which is the
        whole rollback — rows beyond the accepted length sit above
        every later query's causal mask (append-only pages make the
        retreat a pure length decrement; contiguous mode overwrites the
        same rows on the next step).

        Greedy acceptance: draft j is accepted iff it equals the
        program's own argmax after consuming rows 0..j-1 AND every
        earlier draft was accepted — so the emitted chain
        ``draft[:a] + preds[a]`` is exactly the argmax chain plain
        greedy decode would produce, token for token.

        Per-request SAMPLING slots never draft (no argmax chain to
        verify); under the static ``use_samp`` arm their row-0 token is
        sampled in-jit through the same per-slot param stack the
        decode programs use."""
        if self._verify_c is None:
            paged = self.cfg.paged
            S = self.cfg.spec_k + 1

            def fn(pb, ids, caches, bt, start, n_draft, key, samp,
                   use_samp):
                TRACE_COUNTS["spec_verify"] += 1
                _shape_note("spec_verify", ids=ids, n_draft=n_draft)
                pos = start[:, None] + jnp.arange(S, dtype=jnp.int32)
                if paged:
                    state = PagedState(block_tables=bt, seq_lens=start)
                    kv = [(c, state) for c in caches]
                else:
                    kv = caches
                logits, new_kv = functional_call(
                    self.model, pb["p"], ids, position_ids=pos,
                    kv_caches=kv, cache_index=start, buffers=pb["b"])
                preds = jnp.argmax(logits, axis=-1)  # [slots, S]
                match = (preds[:, :-1] == ids[:, 1:]) & \
                    (jnp.arange(S - 1, dtype=n_draft.dtype)[None, :]
                     < n_draft[:, None])
                # accepted = longest all-accepted prefix of the drafts
                accepted = jnp.sum(
                    jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
                if use_samp:
                    greedy_mask, temp, tk, tp = samp
                    s0 = jax.random.categorical(
                        key, G.process_logits_batch(
                            logits[:, 0], temp, tk, tp), axis=-1)
                    preds = preds.at[:, 0].set(
                        jnp.where(greedy_mask, preds[:, 0], s0))
                if paged:
                    return preds, accepted, [c for c, _ in new_kv]
                return preds, accepted, new_kv
            self._verify_c = jax.jit(fn, static_argnums=(8,),
                                     donate_argnums=(2,))
        return self._verify_c

    # ---------------- prefix cache ----------------
    def _prefill_ids(self, req: Request) -> np.ndarray:
        """The token sequence admission must prefill for ``req``: its
        prompt — plus, for a request re-queued by crash recovery,
        every token it had already generated (host-side truth the
        quarantined step cannot lose). Replaying prompt+history
        through the SAME chunked-prefill program recomputes the KV the
        quarantine discarded and samples the NEXT token of the greedy
        chain, so greedy outputs stay bit-identical to a fault-free
        run."""
        if req.output:
            return np.concatenate(
                [req.prompt, np.asarray(req.output, np.int64)])
        return req.prompt

    def _match_prefix(self, req: Request, ids=None):
        """Longest cached block-aligned prefix for the request's
        prefill ids (prompt, or prompt+history on replay; ``ids``
        passes the caller's already-built array — a pool-blocked head
        request retries every tick and must not re-concatenate):
        (hashes, matched entries, prefix_len, full_cover), with the
        full-cover clamp — a fully-cached sequence still recomputes
        its LAST token so prefill has a row to sample from
        (``full_cover`` reports that the clamp fired: the recompute
        row lands inside the last shared page). The single site for
        the clamp rule: both cache modes' admission arms go through
        here."""
        if ids is None:
            ids = self._prefill_ids(req)
        if req._hashes is None:
            req._hashes = block_hashes(
                ids, self._prefix_block,
                namespace=request_namespace(req))
        hashes = req._hashes
        matched = self._prefix.match(hashes)
        prefix_len = len(matched) * self._prefix_block
        full_cover = prefix_len >= ids.size
        if full_cover:
            prefix_len = ids.size - 1
        return hashes, matched, prefix_len, full_cover

    def _note_prefix(self, prefix_len: int, n: int,
                     req: Optional[Request] = None):
        tenant = (req.tenant or "-") if req is not None else "-"
        tr = self._tracer
        if tr is not None and req is not None \
                and tr.want_request(req.rid):
            tr.request(req.rid, "prefix_lookup",
                       hit_tokens=int(prefix_len),
                       prompt_tokens=int(n))
        if n < self._prefix_block:
            # no full block: block_hashes yields nothing, so the prompt
            # can never hit — counting it as a miss would drag the
            # hit-rate toward 0 on short-prompt traffic the cache was
            # never meant to serve
            return
        st = self.prefix_stats
        st["prompt_tokens"] += n
        if prefix_len > 0:
            st["hits"] += 1
            st["hit_tokens"] += prefix_len
        else:
            st["misses"] += 1
        if self._tel is not None:
            self._tel.on_prefix(prefix_len, n,
                                self._prefix.cached_pages,
                                tenant=tenant)

    def _evict_pages(self, n_pages: int,
                     prefer_ns: Optional[str] = None) -> int:
        """Reclaim pool pages from cache-only prefix entries (LRU).
        ``prefer_ns``: evict the requesting tenant's own namespace
        first — its pool pressure spends its own cold entries before
        it can flush another tenant's cached system prompt."""
        if self._prefix is None or not self.cfg.paged:
            return 0
        freed = self._prefix.evict(self.pool, n_pages,
                                   prefer_ns=prefer_ns)
        if freed:
            self.prefix_stats["evictions"] += freed
            if self._tel is not None:
                self._tel.on_prefix_evict(freed,
                                          self._prefix.cached_pages)
            if self._tracer is not None:
                self._tracer.engine_event(
                    "prefix_evict", freed_pages=int(freed),
                    cached_pages=int(self._prefix.cached_pages))
        return freed

    def _cow_block(self, slot: int, block_idx: int) -> bool:
        """Copy-on-write the shared page at ``block_idx`` of ``slot``:
        fresh page (evicting if the free list is dry), device copy,
        block-table swap. False when no page can be found."""
        old = int(self.pool.block_tables[slot, block_idx])
        if self.pool.free_pages == 0 and not self._evict_pages(1):
            return False
        new = self.pool.cow(slot, block_idx)
        if new is None:
            return False
        prof = self._prof
        p_want = prof is not None and prof.want("page_copy")
        t0 = time.perf_counter()
        with self._ctx():
            self.layer_caches = self._copy_page()(
                self.layer_caches, old, new)
        if p_want:
            # t_call == t0: the COW has no host scheduling stage
            prof.observe("page_copy", t0, t0, time.perf_counter(),
                         self.layer_caches[0].k_pages)
        self.prefix_stats["cow_copies"] += 1
        tr = self._tracer
        if tr is not None:
            # rid is unknown during admission claim (the slot joins
            # _slot_req only after the whole wave claims cleanly)
            req = self._slot_req.get(slot)
            if req is not None and tr.want_request(req.rid):
                tr.request(req.rid, "cow", slot=slot,
                           block=int(block_idx), src_page=old,
                           dst_page=int(new))
            elif req is None:
                tr.engine_event("cow", slot=slot, block=int(block_idx),
                                src_page=old, dst_page=int(new))
        return True

    def _cow_for_decode(self, k_steps: int):
        """Before a decode dispatch: every page the next ``k_steps``
        appends can touch must be exclusively owned — a shared page
        (prefix store or another slot holds a ref) is copied first, so
        a decode write can never mutate a cached prefix entry. The
        admission path's block-aligned sharing makes this structurally
        rare (writes land past the shared prefix), but it is the
        invariant the prefix cache's correctness rests on — so the
        check deliberately reads the pool's REAL refcounts for the
        write-window pages (≤2 per slot per dispatch), not admission
        bookkeeping: it must catch sharing from any source, as the
        guard test's external retain() does."""
        if self._prefix is None or not self.cfg.paged \
                or self.pool.shared_pages == 0:
            return
        ps = self.cfg.page_size
        for slot in range(self.cfg.max_slots):
            if not self.active[slot]:
                continue
            lo = int(self.seq_lens[slot]) // ps
            hi = (int(self.seq_lens[slot]) + max(k_steps, 1) - 1) // ps
            n_have = len(self.pool.pages_of[slot])
            for b_idx in range(lo, min(hi, n_have - 1) + 1):
                page = int(self.pool.block_tables[slot, b_idx])
                if self.pool.ref.get(page, 0) > 1:
                    if not self._cow_block(slot, b_idx):
                        raise RuntimeError(
                            "copy-on-write needs a free page but the "
                            "pool is exhausted — size n_pages up")

    def _paged_prefix_admit(self, slot: int, req: Request, need: int,
                            ids=None):
        """Claim pages for a request, sharing the longest cached
        block-aligned prefix. Returns (prefix_len, hashes) or None when
        the pool can't fit the request (slot left clean). A FULL-cover
        hit (prompt entirely cached) adopts every matched page and
        recomputes only the last token — the page it rewrites is
        shared, so it is copy-on-written first."""
        pool = self.pool
        store = None if self._prefix_disabled() else self._prefix
        hashes: List[bytes] = []
        shared: List[int] = []
        prefix_len = 0
        full_cover = False
        if store is not None:
            hashes, shared, prefix_len, full_cover = \
                self._match_prefix(req, ids)
        # feasibility precheck: pages the slot still needs from the
        # free list (adopted pages aren't on it; the full-cover COW
        # consumes one more). A pool-blocked request retries every
        # scheduler tick — without this gate each retry would pay the
        # adopt/release churn, a wasted COW device copy, and worst of
        # all drain LRU store entries via eviction that can't cover
        # the shortfall anyway.
        required = pool.pages_needed(need) - len(shared)
        if full_cover and shared:
            required += 1  # the COW's fresh private page
        supply = pool.free_pages
        # eviction supply reads the REAL store, not the degradation-
        # gated one: min_service only disables ADOPTION — pages the
        # store retains stay evictable, and hiding them here would
        # turn a reclaimable pool into a spurious "size n_pages up"
        # crash (or a permanent pool-block that pins the ladder)
        evict_src = self._prefix
        if required > supply and evict_src is not None:
            supply += evict_src.evictable_pages(pool, exclude=shared)
            if full_cover and shared \
                    and pool.ref.get(shared[-1], 0) == 1:
                # the COW un-borrows the last shared page (back to
                # store-only), so eviction can reclaim it afterwards
                supply += 1
        if required > supply:
            return None  # can't fit even after eviction
        try:
            if shared:
                if not pool.adopt(slot, shared):
                    # over-long share can't happen while add_request
                    # bounds prompt+max_new to max_len — but a silent
                    # no-op here would mean attending over sink pages
                    raise RuntimeError(
                        f"prefix share of {len(shared)} pages exceeds "
                        f"max_pages_per_slot={pool.max_pages_per_slot}")
                if full_cover:
                    # the clamped recompute row ALWAYS lands inside the
                    # last shared page (for page_size 1 it IS that
                    # page, aligned or not — the modulo is no proxy)
                    if not self._cow_block(slot, len(shared) - 1):
                        # can't afford the copy: fall back to
                        # recomputing the whole last block into a fresh
                        # page instead
                        pool.release(pool.pages_of[slot].pop())
                        self.pool.block_tables[slot, len(shared) - 1] = 0
                        prefix_len = (len(shared) - 1) * \
                            self.cfg.page_size
            if not pool.alloc(slot, need):
                missing = pool.pages_needed(need) \
                    - len(pool.pages_of[slot])
                self._evict_pages(missing - pool.free_pages,
                                  prefer_ns=request_namespace(req))
                if not pool.alloc(slot, need):
                    pool.free(slot)  # releases adopted refs too
                    return None
            return prefix_len, hashes
        except BaseException:
            # an error mid-claim (e.g. the COW device dispatch) must
            # leave the slot clean: it never joined the wave's jobs
            # list, so the admission rollback won't free it — stale
            # adopted pages here would wedge the next adopt() or let a
            # later occupant write SHARED pages without copy-on-write
            pool.free(slot)
            raise

    def _prefix_store_insert(self, slot: int, prompt: np.ndarray,
                             hashes: List[bytes], n_matched: int,
                             ns: str = ""):
        """After a request's prefill is dispatched, publish its full
        prompt blocks to the store. Paged: refcount the slot's pages
        (zero copies — the chunk programs already queued the writes on
        the stream, so any future reader is ordered after them).
        Contiguous: slice the new blocks out of the slot's rows."""
        store = None if self._prefix_disabled() else self._prefix
        if store is None or not hashes:
            return
        B = self._prefix_block
        if self.cfg.paged:
            for i, digest in enumerate(hashes):
                store.insert(digest, int(self.pool.block_tables[slot, i]),
                             self.pool, ns=ns)
        else:
            for i in range(n_matched, len(hashes)):
                if hashes[i] in store:
                    continue
                with self._ctx():
                    k, v = self._read_block_contig()(
                        self.caches, slot, i * B)
                # protect the chain being inserted: same-ns eviction
                # must not eat this prompt's own earlier blocks
                store.insert(hashes[i], k, v, ns=ns, protect=hashes)
            evicted = store.evictions - self.prefix_stats["evictions"]
            if evicted > 0:
                self.prefix_stats["evictions"] = store.evictions
                if self._tel is not None:
                    self._tel.on_prefix_evict(evicted,
                                              store.cached_pages)

    # ---------------- scheduling ----------------
    def _admit_dispatch(self):
        """Dispatch prefill programs for every admissible queued request
        WITHOUT syncing the host (JAX dispatch is async: everything
        queues on the device stream behind any in-flight decode chunk).
        Default path: prefix-cache lookup + single-program CHUNKED
        prefill; ``PT_FLAGS_prefill_chunk=0`` selects the legacy
        per-bucket path (the parity oracle). Returns the pending
        (req, slot, first_token_future) list for
        ``_admit_integrate``."""
        # fresh verdict each attempt: the flag self-heals the moment an
        # admission pass no longer blocks on the pool (the previous
        # verdict survives in _pool_blocked_prev for the policy's
        # preemption window, which runs before this pass can re-judge)
        self._pool_blocked_prev = self._pool_blocked
        self._pool_blocked = False
        if not self._queue:
            return []
        if self._draining and (not self._chunk_len
                               or not self._drain_pending()):
            # drain(): stop admitting FRESH requests — they stay
            # queued for a resume() or the router to re-dispatch. The
            # exception: crash-recovery replays (requests that were
            # already in flight once) stay admissible on the chunked
            # path, or a quarantine mid-drain would silently strand
            # its victims behind a closed admission gate
            return []
        inj = self._injector
        if inj is not None and inj.fire("pool"):
            # simulated KV-pool exhaustion: admission blocks this tick
            # exactly like a real pool-blocked head request would —
            # backpressure()/healthz report saturated, the ladder sees
            # a capacity signal (never a fault), and the next clean
            # tick self-heals
            self._note_fault("pool", "admission")
            self._pool_blocked = True
            return []
        if self._chunk_len:
            return self._admit_dispatch_chunked()
        return self._admit_dispatch_bucketed()

    def _admit_dispatch_chunked(self):
        """Chunked admission wave: claim slots + pages (prefix-aware)
        for every admissible request, then drive ONE fixed-shape chunk
        program over all of them together — request A's chunk 2 and
        request B's chunk 0 ride the same call, packed behind the
        in-flight decode chunk. All-or-nothing on error: a failure
        mid-wave rolls every claimed request back into the queue (FIFO
        preserved) before propagating. Within one wave a request
        cannot hit blocks published by an earlier request of the SAME
        wave (store inserts land at the end); across waves it does."""
        C = self._chunk_len
        cfg = self.cfg
        ctl = self._degctl
        shed = ctl is not None and ctl.shed_batch
        throttle = ctl is not None and ctl.throttle
        jobs = []  # [req, slot, prefix_len, hashes, n_matched, cursor,
        #            ids] — ids: the prefill token sequence (prompt, or
        #            prompt+history for a crash-recovery replay)
        # rids deferred this wave (shed batch / draining-fresh /
        # just-preempted): they stay IN the queue at their position —
        # deferral is a skip, never a reorder. fifo_cursor: the FIFO
        # path's wave-local [snapshot, index] (see _pick_admission)
        skip = set()
        fifo_cursor = [None, 0]
        if self._sched is not None:
            # the policy's preemption window: it may release slots
            # (engine.preempt → requeued at the front) for this very
            # wave; preempted rids must not re-admit in the same wave
            # (their freed slots are what the wave is FOR)
            skip.update(self._sched.before_admission(self) or ())
        try:
            while self._free_heap:
                if throttle and jobs:
                    break  # degraded: at most one admission per wave
                req = self._pick_admission(skip, fifo_cursor)
                if req is None:
                    break
                if shed and req.slo == "batch":
                    # degradation L1+: defer (never drop) batch-class
                    # admissions; they keep their queue position
                    skip.add(req.rid)
                    continue
                if self._draining and not (req._retries or req.output):
                    # draining: only in-flight-once replays admit;
                    # fresh requests defer in place
                    skip.add(req.rid)
                    continue
                slot = self._free_heap[0]  # peek; claimed below
                ids = self._prefill_ids(req)
                n = ids.size
                # replay: the history is part of ids, so the new-token
                # budget shrinks by what was already generated — the
                # page need is identical to the original admission's
                need = n + req.max_new_tokens - len(req.output)
                prefix_len, hashes, n_matched = 0, [], 0
                if cfg.paged:
                    got = self._paged_prefix_admit(slot, req, need, ids)
                    if got is None:
                        if not self.active.any() and not jobs:
                            raise RuntimeError(
                                f"request {req.rid} needs "
                                f"{self.pool.pages_needed(need)} pages "
                                f"but the pool has "
                                f"{self.pool.free_pages} free with no "
                                "request running — size n_pages up")
                        self._pool_blocked = True
                        break  # pool exhausted: wait for a finisher
                    prefix_len, hashes = got
                    n_matched = prefix_len // cfg.page_size
                elif not self._prefix_disabled() \
                        and self._prefix is not None:
                    hashes, matched, prefix_len, _full = \
                        self._match_prefix(req, ids)
                    n_matched = len(matched)
                    B = self._prefix_block
                    with self._ctx():
                        for i, (kb, vb) in enumerate(matched):
                            self.caches = self._insert_prefix_contig()(
                                self.caches, kb, vb, slot, i * B)
                # commit: head popleft when possible (the FIFO fast
                # path's O(1) twin), else remove by IDENTITY (the
                # policy may have picked mid-queue; deque.remove
                # matches `is` first)
                if self._queue and self._queue[0] is req:
                    self._queue.popleft()
                else:
                    self._queue.remove(req)
                if skip:
                    # cursor mode: the wave snapshot may still hold
                    # this (now-claimed) request — mark it consumed
                    skip.add(req.rid)
                heapq.heappop(self._free_heap)
                self.active[slot] = True
                req.slot = slot
                self._slot_req[slot] = req
                if self._sched is not None:
                    self._sched.note_admit(self, req)
                # 6th element: the prefill cursor (starts at the
                # prefix boundary; _drive_prefill_chunks advances it —
                # prefix_len itself stays pristine for the stats
                # commit)
                jobs.append(
                    [req, slot, prefix_len, hashes, n_matched,
                     prefix_len, ids])
            if not jobs:
                return []
            return self._drive_prefill_chunks(jobs)
        except BaseException as e:
            # all-or-nothing rollback: free claimed slots/pages and
            # requeue in submission order so a caught admission error
            # neither shrinks the engine nor strands a request
            for req, slot, *_ in reversed(jobs):
                self.active[slot] = False
                self._slot_req.pop(slot, None)
                req.slot = None
                heapq.heappush(self._free_heap, slot)
                if self.pool is not None:
                    self.pool.free(slot)
                self._queue.appendleft(req)
            if isinstance(e, InjectedFault) \
                    and self._recovery_mode != "off":
                # injected prefill-seam fault: the rollback above IS
                # the quarantine (requests back in the queue, slots
                # and pages clean) — count the recovery, charge each
                # wave member one retry, and admit again next tick
                self._after_admission_fault(e, [j[0] for j in jobs])
                return []
            raise

    def _drive_prefill_chunks(self, jobs):
        """Host loop over suffix chunks for a wave of claimed requests.
        Each iteration packs every still-prefilling request's next C
        tokens into one [slots, C] call; slots with nothing to prefill
        (or actively decoding) carry the ``start = max_len`` sentinel —
        their writes drop in-program and their sampled output is
        ignored."""
        C = self._chunk_len
        cfg = self.cfg
        sentinel = cfg.max_len
        pending = []
        remaining = list(jobs)
        # block tables are fixed once the claim loop ends — upload once
        # per wave, not per chunk iteration
        bt = (jnp.asarray(self.pool.block_tables) if cfg.paged
              else jnp.zeros((1,), jnp.int32))
        # first-token sampling params for the wave's requests (slots
        # not in the wave carry defaults — their sampled output is the
        # ignored sentinel row)
        use_samp, samp = self._slot_sampling(
            [(job[1], job[0]) for job in jobs])
        tr = self._tracer
        while remaining:
            t0 = time.perf_counter()
            # fault seam: an injected fault here quarantines the WHOLE
            # wave through the admission rollback (slots/pages freed,
            # requests requeued, one retry charged each)
            self._fault_point("prefill_chunk")
            ids = np.zeros((cfg.max_slots, C), np.int64)
            start = np.full((cfg.max_slots,), sentinel, np.int32)
            last_idx = np.zeros((cfg.max_slots,), np.int32)
            finishing = []
            packed = 0
            call_shares = [] if self._cost_enabled else None
            for job in remaining:
                req, slot, p, job_ids = job[0], job[1], job[5], job[6]
                take = min(C, job_ids.size - p)
                ids[slot, :take] = job_ids[p:p + take]
                start[slot] = p
                if p + take >= job_ids.size:
                    last_idx[slot] = job_ids.size - 1 - p
                    finishing.append(job)
                job[5] = p + take
                packed += take
                if call_shares is not None:
                    call_shares.append((req, take))
                if tr is not None and tr.want_request(req.rid):
                    tr.request(req.rid, "prefill_chunk", start=int(p),
                               tokens=int(take), slot=slot)
            self._key, sub = jax.random.split(self._key)
            caches = self.layer_caches if cfg.paged else self.caches
            prof = self._prof
            p_want = prof is not None and prof.want("prefill_chunk")
            p_dec = None
            t_call = time.perf_counter()
            with self._ctx():
                toks, caches = self._prefill_chunked()(
                    self._pb, jnp.asarray(ids, jnp.int32), caches, bt,
                    jnp.asarray(start), jnp.asarray(last_idx), sub,
                    samp, use_samp)
            if cfg.paged:
                self.layer_caches = caches
            else:
                self.caches = caches
            if p_want:
                # sampled: measure the chunk program itself (its
                # device time otherwise surfaces only inside the NEXT
                # decode/verify step's sync window)
                p_dec = prof.observe("prefill_chunk", t0, t_call,
                                     time.perf_counter(), toks)
                if call_shares:
                    # prefill cost attributes only on MEASURED calls:
                    # an unsampled chunk is async — its device time
                    # surfaces in the next step's sync window, and
                    # charging host-dispatch wall as device cost
                    # would be dishonest. Reconciliation holds at
                    # profile_sample_every=1.
                    self._attribute_cost(
                        "prefill_chunk", p_dec["device_ms"], True,
                        call_shares)
            if tr is not None:
                # unsampled dispatches stay a dispatch-only span: the
                # chunk program is async — its device time surfaces in
                # the NEXT decode/verify step's sync window, so only
                # host dispatch wall is honest without the profiler
                seq = tr.next_step()
                if tr.want_step(seq):
                    tr.step(seq, "prefill_chunk", t0,
                            time.perf_counter(),
                            prefilling=len(remaining),
                            tokens_packed=packed, chunk=C,
                            chunk_budget_spent=packed,
                            occupancy=float(self.active.sum())
                            / cfg.max_slots,
                            rids=[int(j[0].rid) for j in remaining],
                            **(dict(p_dec, profiled=True)
                               if p_dec is not None else {}))
            for job in finishing:
                pending.append((job[0], job[1], job[6].size,
                                toks[job[1]]))
            done_slots = {j[1] for j in finishing}  # slots are unique
            remaining = [j for j in remaining if j[1] not in done_slots]
        # the wave is committed: only now do the prompts' blocks
        # publish and hit/miss stats count — the all-or-nothing
        # rollback path can't double-count a requeued request. Insert
        # BEFORE note so the cached-pages gauge reflects this
        # request's own published blocks.
        for req, slot, prefix_len, hashes, n_matched, _cursor, ids_arr \
                in jobs:
            self._prefix_store_insert(slot, ids_arr, hashes, n_matched,
                                      ns=request_namespace(req))
            if self._prefix is not None and not self._prefix_disabled():
                self._note_prefix(prefix_len, ids_arr.size, req)
        return pending

    def _admit_dispatch_bucketed(self):
        """Legacy per-bucket admission (PT_FLAGS_prefill_chunk=0): one
        jit specialization per seq bucket, whole-prompt recompute — the
        pre-chunking trace, kept as the parity oracle."""
        pending = []
        while self._queue and self._free_heap:
            req = self._queue[0]
            slot = self._free_heap[0]  # peek; claimed only on success
            ids_arr = self._prefill_ids(req)
            n = ids_arr.size
            # paged: allocate for the full prefill bucket too — the
            # prefill scatter writes bucket//page_size whole pages, and
            # a bucket coarser than prompt+max_new must not spill into
            # the sink page or pages owned by other slots
            need = max(n + req.max_new_tokens - len(req.output),
                       self._bucket(n))
            if self.cfg.paged and not self.pool.alloc(slot, need):
                if not self.active.any() and not pending:
                    raise RuntimeError(
                        f"request {req.rid} needs "
                        f"{self.pool.pages_needed(need)} pages but the "
                        f"pool has {self.pool.free_pages} free with no "
                        "request running — size n_pages up")
                self._pool_blocked = True
                break  # pool exhausted: wait for a finisher
            self._queue.popleft()
            heapq.heappop(self._free_heap)
            t0 = time.perf_counter()
            try:
                bucket = self._bucket(n)
                padded = np.zeros((1, bucket), np.int64)
                padded[0, :n] = ids_arr
                one_caches = self.model.init_kv_caches(
                    1, bucket, dtype=self.cache_dtype)
                self._key, sub = jax.random.split(self._key)
                use_samp = self._req_nondefault(req)
                samp = (
                    jnp.asarray([self._req_greedy(req)]),
                    jnp.asarray([max(
                        req.temperature if req.temperature is not None
                        else self.cfg.temperature, 1e-6)], jnp.float32),
                    jnp.asarray([req.top_k or 0], jnp.int32),
                    jnp.asarray([req.top_p if req.top_p is not None
                                 else 1.0], jnp.float32))
                prof = self._prof
                p_want = prof is not None \
                    and prof.want("prefill_bucket")
                p_dec = None
                t_call = time.perf_counter()
                with self._ctx():
                    first_dev, filled = self._prefill()(
                        self._pb, jnp.asarray(padded, jnp.int32),
                        one_caches, n - 1, sub, samp, use_samp)
                    if p_want:
                        p_dec = prof.observe(
                            "prefill_bucket", t0, t_call,
                            time.perf_counter(), (first_dev, filled))
                        if self._cost_enabled:
                            # single-request program: the whole
                            # measured wall is this request's
                            self._attribute_cost(
                                "prefill_bucket", p_dec["device_ms"],
                                True, [(req, n)])
                    if self.cfg.paged:
                        self.layer_caches = self._scatter_paged()(
                            self.layer_caches, filled,
                            jnp.asarray(self.pool.block_tables[slot]))
                    else:
                        self.caches = self._insert_contig()(
                            self.caches, filled, slot)
            except BaseException:
                # the heap no longer self-heals from the active mask:
                # give the claimed slot (and its pages) back AND requeue
                # the request before propagating, or a caught admission
                # error would shrink the engine by one slot forever and
                # strand the request's rid incomplete. Requests admitted
                # EARLIER in this call are already active — integrate
                # them now (lengths/first tokens) so a caller that
                # catches the error doesn't decode them from seq_len 0
                heapq.heappush(self._free_heap, slot)
                if self.pool is not None:
                    self.pool.free(slot)
                self._queue.appendleft(req)
                self._admit_integrate(pending)
                raise
            # mark the slot taken now so the next iteration can't hand
            # it out again; lengths/last_tok land at integrate
            self.active[slot] = True
            req.slot = slot
            self._slot_req[slot] = req
            pending.append((req, slot, n, first_dev))
            tr = self._tracer
            if tr is not None:
                seq = tr.next_step()
                if tr.want_step(seq):
                    tr.step(seq, "prefill_bucket", t0,
                            time.perf_counter(), rid=int(req.rid),
                            bucket=int(bucket), prompt_tokens=int(n),
                            occupancy=float(self.active.sum())
                            / self.cfg.max_slots,
                            **(dict(p_dec, profiled=True)
                               if p_dec is not None else {}))
        return pending

    def _admit_integrate(self, pending):
        """Sync each admitted request's first token (a scalar transfer)
        and finish its bookkeeping; the sequence joins the NEXT decode
        chunk. ``n_ctx`` is the prefilled context length — the prompt,
        or prompt+history for a crash-recovery replay, whose original
        TTFT and admit instant are preserved (per-request TPOT stays
        the honest wall from FIRST admission to last token, fault
        stalls included)."""
        for req, slot, n_ctx, first_dev in pending:
            first = int(first_dev)  # scalar, not [1, bucket, vocab]
            now = time.perf_counter()
            fresh = req.ttft_ms is None
            if fresh:
                req._admit_t = now
                req.ttft_ms = (now - req._submit_t) * 1e3
            req.output.append(first)
            # the prefill-sampled first token counts toward the
            # flight-data token counter too (telemetry's on_admit/
            # on_readmit make the same call) — a prefill-heavy window
            # must not read as zero tokens
            self._tokens_emitted += 1
            self.seq_lens[slot] = n_ctx
            self.last_tok[slot] = first
            if self._tel is not None:
                if fresh:
                    self._tel.on_admit(req.ttft_ms)
                else:
                    self._tel.on_readmit()
            tr = self._tracer
            if tr is not None and tr.want_request(req.rid):
                if fresh:
                    # the span covers queue wait + prefill: exactly TTFT
                    tr.request(req.rid, "admitted", t0=req._submit_t,
                               t1=now, slot=slot,
                               ttft_ms=req.ttft_ms, first_tokens=1,
                               prompt_tokens=int(req.prompt.size))
                else:
                    tr.request(req.rid, "readmitted", slot=slot,
                               retries=int(req._retries),
                               replayed_tokens=int(n_ctx
                                                   - req.prompt.size))
            self._maybe_finish(slot, first)

    def _admit(self):
        """Blocking admission (dispatch + integrate) with the same
        crash-recovery coverage as the step paths: JAX dispatch is
        async, so a prefill program's runtime failure surfaces at its
        first-token SYNC in ``_admit_integrate`` — without this guard
        the exact fault class ``serve_recovery`` promises to survive
        would crash the idle-engine admission path."""
        try:
            self._admit_integrate(self._admit_dispatch())
        except BaseException as e:
            if not self._recoverable(e):
                raise
            self._recover_step(e, self.active.copy(), "admit")

    def _integrate_guarded(self, pending, program: str):
        """``_admit_integrate`` as a recovery point: the first-token
        sync is where an async prefill failure actually lands."""
        try:
            self._admit_integrate(pending)
        except BaseException as e:
            if not self._recoverable(e):
                raise
            self._recover_step(e, self.active.copy(), program)

    def _slo_bucket(self, slo: str) -> Dict[str, int]:
        st = self.slo_stats.get(slo)
        if st is None:
            st = self.slo_stats[slo] = new_slo_bucket()
        return st

    def _tenant_bucket(self, tenant: Optional[str]) -> Dict[str, float]:
        """Cumulative per-tenant host counters (``"-"`` = untagged) —
        written at finish/preempt on the scheduler thread, read via
        ``tenant_snapshot()``."""
        key = tenant or "-"
        st = self.tenant_stats.get(key)
        if st is None:
            st = self.tenant_stats[key] = {
                "finished": 0, "cancelled": 0, "timeouts": 0,
                "failed": 0, "tokens": 0, "device_ms": 0.0,
                "slo_met": 0, "slo_violated": 0, "preemptions": 0,
            }
        return st

    def _finish_accounting(self, req: Request, reason: str):
        """Shared finish/cancel bookkeeping: per-request TPOT, SLO
        attainment (host ``slo_stats`` + telemetry counters + goodput
        gauge), and the tracer's closing ``active`` span. Pure host
        arithmetic over values the scheduler already holds."""
        now = time.perf_counter()
        req.finish_reason = reason
        n_decode = len(req.output) - 1  # first token priced into TTFT
        if req._admit_t and n_decode > 0:
            req.tpot_ms = (now - req._admit_t) * 1e3 / n_decode
        tst = self._tenant_bucket(req.tenant)
        tst["tokens"] += len(req.output)
        if reason in ("cancel", "timeout", "failed"):
            tst[{"cancel": "cancelled", "timeout": "timeouts",
                 "failed": "failed"}[reason]] += 1
        else:
            tst["finished"] += 1
        if req.slo is not None and reason == "cancel":
            self._slo_bucket(req.slo)["cancelled"] += 1
        elif req.slo is not None and reason in ("timeout", "failed"):
            # an expired or retries-exhausted request never delivered:
            # forced SLO violation — a timed-out request that happened
            # to meet its TTFT must not inflate goodput
            st = self._slo_bucket(req.slo)
            req.slo_met = False
            st["violated"] += 1
            tst["slo_violated"] += 1
            if reason == "timeout":
                st["timeouts"] += 1
            st["total_tokens"] += len(req.output)
            if self._tel is not None:
                self._tel.on_slo(req.slo, False,
                                 tenant=req.tenant or "-")
        elif req.slo is not None:
            st = self._slo_bucket(req.slo)
            ttft_ok = (req.ttft_target_ms is None
                       or (req.ttft_ms is not None
                           and req.ttft_ms <= req.ttft_target_ms))
            tpot_ok = (req.tpot_target_ms is None or req.tpot_ms is None
                       or req.tpot_ms <= req.tpot_target_ms)
            req.slo_met = ttft_ok and tpot_ok
            st["met" if req.slo_met else "violated"] += 1
            tst["slo_met" if req.slo_met else "slo_violated"] += 1
            if not ttft_ok:
                st["ttft_violations"] += 1
            if not tpot_ok:
                st["tpot_violations"] += 1
            st["total_tokens"] += len(req.output)
            if req.slo_met:
                st["met_tokens"] += len(req.output)
            if self._tel is not None:
                self._tel.on_slo(req.slo, req.slo_met,
                                 tenant=req.tenant or "-")
        tr = self._tracer
        if tr is not None and tr.want_request(req.rid):
            t0 = req._admit_t or now
            if reason == "cancel":
                tr.request(req.rid, "cancel",
                           stage="active" if req._admit_t else "queued",
                           tokens=len(req.output))
            else:
                tr.request(req.rid, "active", t0=t0, t1=now,
                           tokens=len(req.output), reason=reason,
                           tpot_ms=req.tpot_ms, slo=req.slo or "",
                           slo_met=req.slo_met)

    def _release_slot(self, slot: int):
        """Return a slot to the scheduler: active flag, length, free
        heap, request map, and (paged) every page ref — the ONE
        teardown path finish and cancel both use."""
        self.active[slot] = False
        self.seq_lens[slot] = 0
        heapq.heappush(self._free_heap, slot)
        del self._slot_req[slot]
        if self.pool is not None:
            self.pool.free(slot)  # releases adopted prefix refs too

    def _maybe_finish(self, slot: int, tok: int):
        req = self._slot_req.get(slot)
        if req is None:
            return
        hit_eos = (req.eos_token_id is not None and tok == req.eos_token_id)
        if hit_eos:
            reason = "eos"
        elif len(req.output) >= req.max_new_tokens:
            reason = "max_new_tokens"
        elif self.seq_lens[slot] + 1 >= self.cfg.max_len:
            reason = "max_len"
        else:
            return
        req.done = True
        self._finished[req.rid] = req
        self._release_slot(slot)
        self._finish_accounting(req, reason)
        if self._cost_enabled:
            # defer the finish-time cost record past the step's
            # attribution pass: this request's final chunk share has
            # not been split yet (flushed in the step wrapper)
            self._cost_pending.append(req)
        if self._tel is not None:
            self._tel.on_finish(req.tpot_ms)

    def cancel(self, request_id: int) -> bool:
        """Cancel a request mid-flight, leak-free: a QUEUED request is
        removed from the queue; an ACTIVE one frees its slot and
        releases every paged KV page AND prefix-cache ref it holds
        (``pool.free`` decrements per-page refcounts, so shared prefix
        pages survive in the store — only this request's ownership is
        dropped). Returns False for unknown / already-finished ids.

        Call from the scheduler thread (the same contract as ``step``):
        an in-flight decode chunk's later writes to the freed pages are
        stream-ordered BEFORE any re-allocation's prefill writes, so
        cancellation never corrupts a successor — the host loop skips
        the cancelled slot's remaining chunk tokens via the ``active``
        mask. The canonical drain primitive ROADMAP item 5's
        timeout/priority scheduler builds on."""
        # queued: remove without ever granting a slot. Snapshot-then-
        # remove-by-identity: add_request may append from a producer
        # thread, and deque iteration raises on concurrent mutation
        # while remove() is a single atomic op.
        req = next((r for r in list(self._queue)
                    if r.rid == request_id), None)
        if req is not None:
            try:
                self._queue.remove(req)
            except ValueError:
                req = None  # raced out of the queue
        if req is None:
            # active: free the slot + pages
            slot = next((s for s, r in self._slot_req.items()
                         if r.rid == request_id), None)
            if slot is None:
                return False
            req = self._slot_req[slot]
            self._release_slot(slot)
        req.done = True
        req.cancelled = True
        self._finished[request_id] = req
        self._finish_accounting(req, "cancel")
        # record immediately: a cancel lands between ticks, with no
        # pending step share to wait for
        self._record_cost_finish(req)
        if self._tel is not None:
            self._tel.on_cancel()
        return True

    # ---------------- resilience ----------------
    def _prefix_disabled(self) -> bool:
        """True while the degradation ladder has switched prefix-cache
        adoption off (min_service) — admission neither matches nor
        publishes; outputs are unchanged, only prefill work grows."""
        return self._degctl is not None and self._degctl.disable_prefix

    def _finish_request(self, req: Request, reason: str):
        """Terminal bookkeeping for a request that leaves the engine
        WITHOUT a normal finish: deadline expiry (``timeout``) or
        retry exhaustion (``failed``). The caller has already removed
        it from the queue or released its slot."""
        req.done = True
        self._finished[req.rid] = req
        self._finish_accounting(req, reason)
        # record immediately: timeout expiry runs at tick START and
        # retry exhaustion inside a quarantine — neither has a pending
        # step share (the failed step's device work is never
        # attributed), and a reclaimed replica may never tick again
        self._record_cost_finish(req)
        if self._tel is not None:
            if reason == "timeout":
                self._tel.on_timeout()
            elif reason == "failed":
                self._tel.on_failed()

    def _expire_deadlines(self):
        """Enforce per-request deadlines: queued requests leave the
        queue, active ones release their slot/pages/prefix refs
        through the one teardown path (``_release_slot``), and both
        finish with reason ``"timeout"``. Checked once per scheduler
        tick — the granularity ``add_request`` validates deadlines
        against."""
        now = time.perf_counter()
        # queued: snapshot-then-remove-by-identity (same concurrency
        # contract as cancel(): add_request may append from a producer
        # thread; deque.remove is a single atomic op)
        for req in list(self._queue):
            if req._deadline_t and now >= req._deadline_t:
                try:
                    self._queue.remove(req)
                except ValueError:
                    continue  # raced out of the queue
                self.resilience_stats["timeouts"] += 1
                self._finish_request(req, "timeout")
        for slot in range(self.cfg.max_slots):
            if not self.active[slot]:
                continue
            req = self._slot_req[slot]
            if req._deadline_t and now >= req._deadline_t:
                self._release_slot(slot)
                self.resilience_stats["timeouts"] += 1
                self._finish_request(req, "timeout")

    def _bump_retry(self, req: Request) -> bool:
        """Charge one replay retry. Returns True while the request may
        be re-queued; past its bound it finishes with reason
        ``"failed"`` (and is pulled from the queue if it sits there)."""
        req._retries += 1
        req._hashes = None  # replay ids differ: stale digests invalid
        limit = (req.max_retries if req.max_retries is not None
                 else self.cfg.max_retries)
        if req._retries > limit:
            try:
                self._queue.remove(req)
            except ValueError:
                pass
            self.resilience_stats["failed"] += 1
            self._finish_request(req, "failed")
            return False
        self.resilience_stats["retries"] += 1
        if self._tel is not None:
            self._tel.on_retry()
        return True

    def _note_fault(self, site: str, program: str):
        st = self.resilience_stats
        st["faults"][site] = st["faults"].get(site, 0) + 1
        if self._tel is not None:
            self._tel.on_fault(site)
        if self._tracer is not None:
            self._tracer.engine_event("fault", site=site,
                                      program=program)

    def _fault_point(self, program: str):
        """One dispatch seam: consult the injector's latency schedule
        (stall in place), then the raising sites — an
        ``InjectedFault`` raised HERE precedes the compiled call, so
        the device cache state is untouched and recovery can requeue
        without rebuilding."""
        inj = self._injector
        if inj is None:
            return
        if inj.fire("latency"):
            self._note_fault("latency", program)
            time.sleep(inj.latency_ms / 1e3)
        for site in ("step", "nan"):
            if inj.fire(site):
                raise InjectedFault(site, program)

    def _recoverable(self, exc: BaseException) -> bool:
        """PT_FLAGS_serve_recovery policy: injected faults always
        recover (unless off); ``auto`` additionally recovers XLA
        runtime errors (device failures) but NEVER host logic errors —
        a plain RuntimeError from scheduler code must propagate;
        ``all`` recovers any Exception."""
        mode = self._recovery_mode
        if mode == "off":
            return False
        if isinstance(exc, InjectedFault):
            return True
        if mode == "all":
            return isinstance(exc, Exception)
        return bool(RUNTIME_ERRORS) and isinstance(exc, RUNTIME_ERRORS)

    def _after_admission_fault(self, exc: InjectedFault,
                               reqs: List[Request]):
        """An injected prefill-seam fault after the wave rollback:
        the quarantine already happened (slots/pages freed, requests
        requeued in order) — account it and charge retries."""
        st = self.resilience_stats
        st["recoveries"] += 1
        site = exc.site
        st["faults"][site] = st["faults"].get(site, 0) + 1
        if site == "nan":
            st["nan_steps"] += 1
            self._nan_dump(exc.program, len(reqs))
        self._faults_tick += 1
        for req in reqs:
            self._bump_retry(req)
        if self._tel is not None:
            self._tel.on_fault(site)
            self._tel.on_recovery(len(reqs))
        if self._tracer is not None:
            self._tracer.engine_event(
                "recovery", site=site, program=exc.program,
                requeued=len(reqs), hard=False)

    def _recover_step(self, exc: BaseException, participants,
                      program: str):
        """Quarantine a failed step: discard its device effects and
        re-queue the affected in-flight requests for deterministic
        replay. Generated tokens live host-side, so replay re-prefills
        prompt+history through the existing chunked-prefill program —
        greedy outputs stay bit-identical to a fault-free run, and the
        replayed admission re-uses the SAME compiled programs (zero
        new specializations, pinned by test).

        Severity: an ``InjectedFault`` fires BEFORE dispatch, so the
        caches are intact — only the step's participants requeue and
        the prefix store survives. Any other (real) runtime failure
        means donated buffers may be gone: every active request
        requeues, the prefix store is dropped and the cache pools are
        rebuilt (same shapes — nothing recompiles)."""
        hard = not isinstance(exc, InjectedFault)
        site = getattr(exc, "site", "error")
        st = self.resilience_stats
        st["recoveries"] += 1
        st["faults"][site] = st["faults"].get(site, 0) + 1
        if site == "nan":
            st["nan_steps"] += 1
        self._faults_tick += 1
        victims = [s for s in range(self.cfg.max_slots)
                   if self.active[s] and (hard or participants[s])]
        requeued = 0
        # reversed + appendleft: victims land at the queue front in
        # ascending slot order, ahead of younger arrivals
        for slot in reversed(victims):
            req = self._slot_req[slot]
            self._release_slot(slot)
            req.slot = None
            if self._bump_retry(req):
                self._queue.appendleft(req)
                requeued += 1
        if hard:
            st["rebuilds"] += 1
            self._rebuild_caches()
        if site == "nan":
            self._nan_dump(program, requeued)
        if self._tel is not None:
            self._tel.on_fault(site)
            self._tel.on_recovery(requeued)
        if self._tracer is not None:
            self._tracer.engine_event(
                "recovery", site=site, program=program,
                requeued=requeued, failed=len(victims) - requeued,
                hard=hard, error=type(exc).__name__)

    def _rebuild_caches(self):
        """Hard crash recovery: after a non-injected runtime failure
        the device cache state is untrusted (the failed call may have
        consumed its donated buffers), so rebuild the pools from
        scratch and DROP the prefix store — paged entries reference
        pages of the discarded pool; contiguous blocks are content-
        addressed but a corrupted write can't be ruled out. Every slot
        was already released by the caller. Same shapes → the jitted
        programs never re-specialize."""
        if self._prefix is not None:
            if self.cfg.paged:
                # all slots freed → every entry is un-borrowed: this
                # empties the store and returns its refs to the pool
                # being discarded (keeps the refcount audit clean)
                self._evict_pages(10 ** 9)
            else:
                self._prefix = ContigPrefixStore(self._prefix.max_blocks)
        self._init_cache_state()

    def _nan_dump(self, program: str, requeued: int):
        """NaN-logits storm postmortem: ride PR 2's flight recorder —
        the dump attaches the lifecycle tracer's tail, so the artifact
        shows WHAT the engine was doing, not just that logits went
        non-finite. Telemetry off → no artifact (host counters still
        count)."""
        if self._tel is None:
            return
        if self._recorder is None:
            self._recorder = observability.FlightRecorder(
                capacity=int(flags.flag("telemetry_flight_window")),
                dump_dir=str(flags.flag("telemetry_dump_dir")))
        # no wall-clock stamp here: dump() writes its own unix_time,
        # and the engine's deterministic paths stay perf_counter-only
        self._recorder.record(
            kind="serve_nan", program=program, requeued=requeued,
            engine=self._tel.engine_id)
        self._recorder.dump(
            f"serving NaN-logits storm in {program} "
            f"(engine {self._tel.engine_id})")

    def _observe_health(self):
        """One degradation-ladder tick: saturation from the live
        admission state, faults accumulated since the last tick.
        Under ``PT_FLAGS_slo_degradation`` (default off) an ACTIVE
        SLO burn-rate alert also counts as saturation pressure — the
        documented read-only ``AlertManager.is_active`` hook: the
        engine is missing latency targets, which is a capacity
        problem, so sustained burn climbs the capacity rungs (shed
        batch / throttle) and never the fault jump. With the flag off
        the ladder's inputs are untouched (outputs pinned
        identical)."""
        if self._degctl is None:
            self._faults_tick = 0
            return
        qd = len(self._queue)
        sat = qd > 0 and (len(self._free_heap) == 0
                          or self._pool_blocked)
        if self._slo_degradation and self._alerts is not None \
                and self._alerts.is_active("slo_burn_rate"):
            sat = True
        before = self._degctl.level
        level = self._degctl.observe(saturated=bool(sat),
                                     faults=self._faults_tick)
        self._faults_tick = 0
        if level != before:
            if self._tel is not None:
                self._tel.on_degradation(level)
            if self._tracer is not None:
                self._tracer.engine_event(
                    "degrade", level=level, previous=before,
                    level_name=self._degctl.name)

    def _drain_pending(self) -> List[Request]:
        """Queued requests that were already in flight once (crash-
        recovery replays): drain() owes these completion — they are
        'in-flight' work even while they sit in the queue."""
        return [r for r in self._queue if r._retries or r.output]

    def drain(self, deadline_ms: Optional[float] = None,
              max_chunk: int = 8) -> dict:
        """Graceful shutdown primitive: stop admitting fresh requests
        (they stay queued for the router to re-dispatch), run every
        in-flight request to completion — INCLUDING requests a
        mid-drain quarantine re-queued for replay — or to
        ``deadline_ms``, past which the stragglers finish with reason
        ``"timeout"`` and their slots/pages/prefix refs are provably
        freed. ``/healthz`` reports ``draining`` (503) for the
        duration and after, until ``resume()``.

        Returns a summary dict whose ``"unfinished"`` entry carries
        the HANDOFF PAYLOAD: one :func:`request_ledger` per request
        that did not finish here — deadline-expired stragglers first
        (ledger captured BEFORE their timeout teardown), then the
        still-queued fresh requests in queue order. A caller (the
        router's rebalance/failover path, or any operator script) can
        re-admit each ledger elsewhere via ``admit_ledger`` and the
        request continues bit-identically with its original TTFT/SLO
        clock."""
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0; got {deadline_ms}")
        self._draining = True
        if self._tel is not None:
            self._tel.on_drain(True)
        if self._tracer is not None:
            self._tracer.engine_event(
                "drain_begin", active=int(self.active.sum()),
                queued=len(self._queue))
        t_end = (None if deadline_ms is None
                 else time.perf_counter() + deadline_ms / 1e3)
        expired = 0
        unfinished: List[dict] = []
        while self.active.any() or self._drain_pending():
            if t_end is not None and time.perf_counter() >= t_end:
                for slot in range(self.cfg.max_slots):
                    if not self.active[slot]:
                        continue
                    req = self._slot_req[slot]
                    # ledger BEFORE teardown: the straggler times out
                    # HERE, but its history survives in the payload so
                    # a caller may still re-admit it elsewhere
                    unfinished.append(request_ledger(req))
                    self._release_slot(slot)
                    self.resilience_stats["timeouts"] += 1
                    self._finish_request(req, "timeout")
                    expired += 1
                for req in self._drain_pending():
                    # replay victims still waiting on a slot expire
                    # too — a drain deadline leaves NOTHING in limbo
                    try:
                        self._queue.remove(req)
                    except ValueError:
                        continue
                    unfinished.append(request_ledger(req))
                    self.resilience_stats["timeouts"] += 1
                    self._finish_request(req, "timeout")
                    expired += 1
                break
            self.step_chunk(max_chunk)
        # fresh requests the closed admission gate kept queued: theirs
        # is the other half of the handoff payload (they stay queued
        # here too, for a resume() — re-admitting one elsewhere makes
        # cancelling it here the caller's job)
        unfinished.extend(request_ledger(r) for r in list(self._queue))
        if self._tracer is not None:
            self._tracer.engine_event(
                "drain_end", expired=expired, queued=len(self._queue))
        return {"drained": True, "expired": expired,
                "active": int(self.active.sum()),
                "queued": len(self._queue),
                "unfinished": unfinished}

    def resume(self):
        """Leave the draining state: admission restarts on the next
        scheduler tick."""
        self._draining = False
        if self._tel is not None:
            self._tel.on_drain(False)

    def resilience_snapshot(self) -> dict:
        """Fault/recovery/degradation counters (plain host counters —
        available even with PT_FLAGS_telemetry=off, like
        prefix/spec/slo snapshots)."""
        if self._san is not None:
            self._san.check_read("resilience_snapshot")
        # copy-on-read: the /healthz scrape thread calls this while
        # the scheduler writes counters; "faults" grows a key on a
        # site's first fault, so both levels iterate list() copies
        st = {k: v for k, v in list(self.resilience_stats.items())}
        st["faults"] = {k: v for k, v in list(st["faults"].items())}
        st["recovery_mode"] = self._recovery_mode
        st["max_retries"] = self.cfg.max_retries
        st["draining"] = self._draining
        st["degradation"] = (self._degctl.snapshot()
                             if self._degctl is not None
                             else {"enabled": False, "level": 0,
                                   "degraded": False})
        st["injector"] = (self._injector.snapshot()
                          if self._injector is not None
                          else {"enabled": False})
        return st

    def step(self) -> bool:
        """One per-token scheduler tick (see ``_step_impl``),
        bracketed by the sanitizer's ownership + invariant hooks and
        the chaos corruption seam — each a single identity check when
        its subsystem is off."""
        san = self._san
        if san is not None:
            san.note_tick("step")
        wd = self._watchdog
        if wd is not None:
            wd.tick_begin()
        out = self._step_impl()
        self._tick_epilogue(wd, san, "step")
        return out

    def _tick_epilogue(self, wd, san, site: str):
        """Shared post-step sequence for the step()/step_chunk()
        wrappers: watchdog diff, deferred cost-finish flush, flight
        tick, chaos corruption seam, sanitizer invariants — ONE list,
        so the two step paths can never desynchronize on a per-tick
        feature. Every hook is a single identity check when its
        subsystem is off."""
        if wd is not None:
            wd.tick_end()
        if self._cost_pending:
            self._flush_cost()
        if self._ts is not None:
            self._flight_tick()
        if self._injector is not None:
            self._corrupt_point()
        if san is not None:
            san.check_tick(self, site)

    def _step_impl(self) -> bool:
        """Admit waiting requests, run one decode step for all active
        slots — or, with speculative decoding enabled and at least one
        slot holding a draft, one multi-token verify pass. Returns
        False when there is nothing left to do."""
        self._expire_deadlines()
        self._observe_health()
        self._admit()
        if not self.active.any():
            return bool(self._queue)
        if self._spec_mode != "off" and not (
                self._degctl is not None and self._degctl.disable_spec):
            drafts = self._propose_drafts()
            if drafts:
                return self._spec_step(drafts)
            self.spec_stats["fallback_steps"] += 1
            if self._tel is not None:
                self._tel.on_spec_fallback()
        t0 = time.perf_counter()
        tr = self._tracer
        seq = tr.next_step() if tr is not None else 0
        adv = {} if tr is not None and tr.want_step(seq) else None
        occ = float(self.active.sum()) / self.cfg.max_slots
        participants = self.active.copy()
        p_dec = None
        try:
            self._fault_point("decode")
            self._cow_for_decode(1)
            use_samp, samp = self._slot_sampling()
            self._key, sub = jax.random.split(self._key)
            toks = jnp.asarray(self.last_tok[:, None], jnp.int32)
            lens = jnp.asarray(self.seq_lens, jnp.int32)
            prof = self._prof
            p_want = prof is not None and prof.want("decode_step")
            t_call = time.perf_counter()
            with self._ctx():
                if self.cfg.paged:
                    state = PagedState(
                        block_tables=jnp.asarray(self.pool.block_tables),
                        seq_lens=lens)
                    nxt, self.layer_caches = self._decode()(
                        self._pb, toks, self.layer_caches, state, sub,
                        samp, use_samp)
                else:
                    nxt, self.caches = self._decode()(
                        self._pb, toks, self.caches, lens, sub, samp,
                        use_samp)
            t_disp = time.perf_counter()
            if p_want:
                # sampled dispatch: MEASURED schedule/dispatch/device
                # decomposition (block_until_ready on the program's
                # own outputs — the sync below was due anyway)
                p_dec = prof.observe("decode_step", t0, t_call,
                                     t_disp, nxt)
                self._hbm_update()
            nxt = np.asarray(nxt)
        except BaseException as e:
            if not self._recoverable(e):
                raise
            self._recover_step(e, participants, "decode")
            return True
        t_sync = time.perf_counter()
        emitted = 0
        cost_shares = [] if self._cost_enabled else None
        for slot in range(self.cfg.max_slots):
            if not self.active[slot]:
                continue
            tok = int(nxt[slot])
            req = self._slot_req[slot]
            req.output.append(tok)
            self.seq_lens[slot] += 1
            self.last_tok[slot] = tok
            emitted += 1
            if adv is not None:
                adv[req.rid] = 1
            if cost_shares is not None:
                cost_shares.append((req, 1))
            self._maybe_finish(slot, tok)
        self._tokens_emitted += emitted
        if cost_shares:
            # attributed device wall: the measured sample when this
            # dispatch was profiled, else the dispatch-done→token-sync
            # host wall (the documented upper-bound fallback)
            self._attribute_cost(
                "decode_step",
                p_dec["device_ms"] if p_dec is not None
                else (t_sync - t_disp) * 1e3,
                p_dec is not None, cost_shares)
        if adv is not None:
            # sampled dispatches report the MEASURED decomposition
            # (schedule_ms/dispatch_ms/device_ms, profiled=True);
            # unsampled keep the SAME schedule/dispatch windows (the
            # stamps cost nothing) plus the honest fallback:
            # sync_wall_ms is the HOST wall from dispatch-done to
            # token sync — an upper bound on device time, not a
            # measurement (the field PR 6 called device_wall_ms_est)
            timing = (dict(p_dec, profiled=True) if p_dec is not None
                      else {"schedule_ms": (t_call - t0) * 1e3,
                            "dispatch_ms": (t_disp - t_call) * 1e3,
                            "sync_wall_ms": (t_sync - t_disp) * 1e3})
            tr.step(seq, "decode", t0, time.perf_counter(),
                    occupancy=occ, tokens_advanced=emitted,
                    chunk_budget_spent=1, advanced=adv, **timing)
        if self._tel is not None:
            self._tel.on_tokens(emitted,
                                (time.perf_counter() - t0) * 1e3)
            self._tel.on_state(*self._tel_state())
        return True

    # ---------------- speculative decoding ----------------
    def _draft_budget(self, slot: int) -> int:
        """Max draft tokens this slot may carry in a verify pass, 0 if
        it is ineligible. O(1) host checks only — callers use it both
        to draft and to SKIP the O(history) drafter scan when a verify
        pass could not dispatch anyway. Eligibility: the request
        decodes GREEDILY (acceptance verifies against the argmax
        chain), has budget for at least one draft + the bonus token,
        and — in ``auto`` mode — hasn't proven its traffic undraftable
        (per-request throttle: after 16 proposed tokens at < 1/8
        acceptance, stop paying the verify width for it)."""
        req = self._slot_req[slot]
        if not self._req_greedy(req):
            return 0
        remaining = min(
            req.max_new_tokens - len(req.output),
            self.cfg.max_len - 1 - int(self.seq_lens[slot]))
        max_d = min(self.cfg.spec_k, remaining - 1)
        if max_d <= 0:
            return 0
        if self._spec_mode == "auto" and req._spec_proposed >= 16 \
                and req._spec_accepted * 8 < req._spec_proposed:
            return 0
        return max_d

    def _propose_drafts(self) -> Dict[int, np.ndarray]:
        """Host-side drafting for the next verify pass: slot → proposed
        token ids (1..spec_k of them) for every eligible slot (see
        ``_draft_budget``) whose drafter actually proposes."""
        if self._drafter is None:
            return {}
        cfg = self.cfg
        out: Dict[int, np.ndarray] = {}
        for slot in range(cfg.max_slots):
            if not self.active[slot]:
                continue
            max_d = self._draft_budget(slot)
            if max_d <= 0:
                continue
            req = self._slot_req[slot]
            hist = np.concatenate(
                [req.prompt, np.asarray(req.output, np.int64)])
            d = np.asarray(self._drafter.propose(hist, max_d)).reshape(-1)
            if d.size:
                out[slot] = d[:max_d]
        return out

    def _spec_step(self, drafts: Dict[int, np.ndarray]) -> bool:
        """One speculative step: dispatch the fixed ``[slots, K+1]``
        verify program over every active slot (drafted slots carry
        their proposals, the rest degrade to a 1-token decode in the
        same call), overlap admission dispatch behind it, then sync and
        advance each slot by ``accepted + 1`` tokens.

        ROLLBACK is the non-advance: the program appended K+1 KV rows
        per active slot, but ``seq_lens`` moves only past the accepted
        prefix — rejected rows sit above every later causal mask and
        are rewritten by the next append at the same positions (paged:
        a pure length decrement on append-only pages; contiguous: same
        rows overwritten next step). The COW guard runs over the FULL
        K+1 write window first: even a pad row's garbage write must
        never land on a page the prefix store (or another slot) still
        shares."""
        cfg = self.cfg
        S = cfg.spec_k + 1
        t0 = time.perf_counter()
        tr = self._tracer
        seq = tr.next_step() if tr is not None else 0
        adv = {} if tr is not None and tr.want_step(seq) else None
        spec_by_rid = {} if adv is not None else None
        occ = float(self.active.sum()) / cfg.max_slots
        chunk_slots = self.active.copy()
        # dispatch-time occupants: the overlapped admission below may
        # preempt + re-claim a slot — the verify pass's tokens must
        # never credit the new occupant (identity-checked at sync)
        chunk_reqs = {s: self._slot_req[s]
                      for s in range(cfg.max_slots) if chunk_slots[s]}
        p_dec = None
        try:
            self._fault_point("verify")
            self._cow_for_decode(S)
            sentinel = cfg.max_len
            ids = np.zeros((cfg.max_slots, S), np.int64)
            start = np.full((cfg.max_slots,), sentinel, np.int32)
            n_draft = np.zeros((cfg.max_slots,), np.int32)
            for slot in range(cfg.max_slots):
                if not chunk_slots[slot]:
                    continue
                ids[slot, 0] = self.last_tok[slot]
                d = drafts.get(slot)
                if d is not None and d.size:
                    ids[slot, 1:1 + d.size] = d
                    n_draft[slot] = d.size
                start[slot] = self.seq_lens[slot]
            use_samp, samp = self._slot_sampling()
            self._key, sub = jax.random.split(self._key)
            bt = (jnp.asarray(self.pool.block_tables) if cfg.paged
                  else jnp.zeros((1,), jnp.int32))
            caches = self.layer_caches if cfg.paged else self.caches
            prof = self._prof
            p_want = prof is not None and prof.want("spec_verify")
            t_call = time.perf_counter()
            with self._ctx():
                preds, accepted, caches = self._verify()(
                    self._pb, jnp.asarray(ids, jnp.int32), caches, bt,
                    jnp.asarray(start), jnp.asarray(n_draft), sub, samp,
                    use_samp)
            if cfg.paged:
                self.layer_caches = caches
            else:
                self.caches = caches
            t_disp = time.perf_counter()
            t_admit0 = t_disp
            if p_want:
                # measured device wall of the verify program itself —
                # blocks BEFORE the overlapped admission dispatch, so
                # the sample is the program, not the overlap window
                p_dec = prof.observe("spec_verify", t0, t_call, t_disp,
                                     (preds, accepted))
                self._hbm_update()
                # admit_dispatch_ms windows the admission work only
                t_admit0 = time.perf_counter()
            # admission dispatches behind the in-flight verify (stream
            # order, exactly like step_chunk's decode-chunk overlap)
            pending = self._admit_dispatch()
            t_admit = time.perf_counter()
            preds_np = np.asarray(preds)  # ONE sync for S tokens/slot
            acc_np = np.asarray(accepted)
        except BaseException as e:
            if not self._recoverable(e):
                raise
            self._recover_step(e, chunk_slots, "verify")
            return True
        t_sync = time.perf_counter()
        emitted = 0
        proposed_tot = accepted_tot = 0
        cost_shares = [] if self._cost_enabled else None
        for slot in range(cfg.max_slots):
            req = chunk_reqs.get(slot)
            if req is None or self._slot_req.get(slot) is not req:
                continue  # finished at sync, or preempted + re-claimed
            n = int(n_draft[slot])
            a = min(int(acc_np[slot]), n)
            toks = [int(ids[slot, 1 + j]) for j in range(a)]
            toks.append(int(preds_np[slot, a]))
            slot_emitted = 0
            for tok in toks:
                if req.done:
                    break  # EOS mid-chain: later tokens discarded
                req.output.append(tok)
                self.seq_lens[slot] += 1
                self.last_tok[slot] = tok
                emitted += 1
                slot_emitted += 1
                if adv is not None:
                    adv[req.rid] = adv.get(req.rid, 0) + 1
                self._maybe_finish(slot, tok)
            if cost_shares is not None and slot_emitted:
                cost_shares.append((req, slot_emitted))
            if spec_by_rid is not None and n:
                spec_by_rid[req.rid] = [n, a]
            if n:
                req._spec_proposed += n
                req._spec_accepted += a
                proposed_tot += n
                accepted_tot += a
                if self._tel is not None:
                    self._tel.on_spec_slot(n, a)
        self.spec_stats["verify_calls"] += 1
        self.spec_stats["proposed"] += proposed_tot
        self.spec_stats["accepted"] += accepted_tot
        self.spec_stats["emitted"] += emitted
        self._tokens_emitted += emitted
        if cost_shares:
            # unsampled fallback conflates the overlapped admission
            # dispatch (the sync_wall_ms caveat); the profiled sample
            # is the verify program alone
            self._attribute_cost(
                "spec_verify",
                p_dec["device_ms"] if p_dec is not None
                else (t_sync - t_disp) * 1e3,
                p_dec is not None, cost_shares)
        if adv is not None:
            # sampled: measured schedule/dispatch/device decomposition
            # (the profiler blocked on the verify outputs BEFORE the
            # admission overlap). Unsampled fallback: same schedule/
            # dispatch windows, plus sync_wall_ms spanning
            # dispatch-done -> token sync — a HOST-wall upper bound
            # that conflates the overlapped admission work, which is
            # reported separately so a reader can subtract it when a
            # first-time prefill compile (host side) dominates
            timing = (dict(p_dec, profiled=True) if p_dec is not None
                      else {"schedule_ms": (t_call - t0) * 1e3,
                            "dispatch_ms": (t_disp - t_call) * 1e3,
                            "sync_wall_ms": (t_sync - t_disp) * 1e3})
            tr.step(seq, "verify", t0, time.perf_counter(),
                    occupancy=occ, tokens_advanced=emitted,
                    chunk_budget_spent=S, advanced=adv,
                    proposed=proposed_tot, accepted=accepted_tot,
                    spec=spec_by_rid,
                    admit_dispatch_ms=(t_admit - t_admit0) * 1e3,
                    **timing)
        self._integrate_guarded(pending, "verify_integrate")
        if self._tel is not None:
            self._tel.on_tokens(emitted, (t_sync - t0) * 1e3)
            self._tel.on_spec_verify(
                proposed_tot, accepted_tot,
                self.spec_stats["accepted"], self.spec_stats["proposed"])
            self._tel.on_state(*self._tel_state())
        return True

    def _slot_budgets(self) -> np.ndarray:
        """Per-slot remaining token budget (max_new_tokens and max_len
        caps) — frozen slots stop advancing inside the fixed-K chunk.

        The scheduler policy's CHUNK-SPLIT seam: ``slot_caps`` may
        shrink individual slots' budgets within the fixed-shape chunk
        (the program still computes every slot's rows — the cap
        bounds which tokens COMMIT, i.e. a tenant's emission and
        paged page-growth per chunk, not the chunk's device time).
        A cap set that would freeze EVERY active slot is ignored: a
        chunk that can emit nothing would spin the scheduler."""
        budget = np.zeros((self.cfg.max_slots,), np.int32)
        for slot in range(self.cfg.max_slots):
            if not self.active[slot]:
                continue
            req = self._slot_req[slot]
            budget[slot] = max(0, min(
                req.max_new_tokens - len(req.output),
                self.cfg.max_len - 1 - int(self.seq_lens[slot])))
        if self._sched is not None:
            caps = self._sched.slot_caps(self)
            if caps is not None:
                capped = np.minimum(
                    budget, np.asarray(caps, np.int32))
                if capped.max(initial=0) > 0 \
                        or budget.max(initial=0) == 0:
                    budget = capped
        return budget

    def step_chunk(self, max_chunk: int = 8) -> bool:
        """One chunked scheduler tick (see ``_step_chunk_impl``),
        bracketed by the sanitizer's ownership + invariant hooks and
        the chaos corruption seam — each a single identity check when
        its subsystem is off."""
        san = self._san
        if san is not None:
            san.note_tick("step_chunk")
        wd = self._watchdog
        if wd is not None:
            wd.tick_begin()
        out = self._step_chunk_impl(max_chunk)
        self._tick_epilogue(wd, san, "step_chunk")
        return out

    def _corrupt_point(self):
        """State-corruption chaos seam: consulted once per tick, AFTER
        the step's host integration. A firing site mangles the
        engine's own bookkeeping — how a ``PT_FLAGS_sanitize`` run
        proves the invariant checker catches real damage (and how the
        sanitizer tests seed their corruptions). Production injector
        specs leave these rates at 0; with no injector this seam is
        never reached."""
        inj = self._injector
        for site in CORRUPT_SITES:
            if inj.fire(site) and self._apply_corruption(site):
                # counted only when damage actually landed — a no-op
                # fire (e.g. scale_desync on a float cache) must not
                # report an injected fault the sanitizer then
                # "misses"
                self._note_fault(site, "corrupt")

    def _apply_corruption(self, site: str) -> bool:
        """Deterministic minimal damage per corruption site, aimed at
        the first active slot (pool/heap when none is active).
        Returns True when state was actually corrupted."""
        slots = [s for s in range(self.cfg.max_slots)
                 if self.active[s]]
        if site == "seq_shrink":
            # cache length falls behind the host token ledger — the
            # replay-source-of-truth desync class
            if slots:
                self.seq_lens[slots[0]] -= 1
                return True
        elif site == "leak_ref":
            if self.pool is not None:
                # a refcount with no owner: the page can never free
                for s in slots:
                    if self.pool.pages_of[s]:
                        p = self.pool.pages_of[s][0]
                        self.pool.ref[p] = self.pool.ref.get(p, 0) + 1
                        return True
            elif self._free_heap:
                # contiguous mode has no pool: leak a slot instead
                heapq.heappop(self._free_heap)
                return True
        elif site == "scale_desync":
            # int8 caches only: shear a dequant-scale array off its
            # payload pool (shape metadata change — no device sync)
            if self.pool is not None:
                c = self.layer_caches[0]
                if c.k_scale is not None:
                    self.layer_caches[0] = c._replace(
                        k_scale=c.k_scale[:, :, :-1])
                    return True
            else:
                from .paged import QuantizedKV

                k, v = self.caches[0]
                if isinstance(k, QuantizedKV):
                    self.caches[0] = (
                        QuantizedKV(k.q, k.scale[:, :-1]), v)
                    return True
        return False

    def _step_chunk_impl(self, max_chunk: int) -> bool:
        """Run ``max_chunk`` decode steps in ONE device program, with
        admission OVERLAPPED: the decode chunk is dispatched first (no
        host sync), then prefill + cache-insert programs for queued
        requests are dispatched behind it on the device stream, and only
        then does the host read the chunk's tokens back. In-flight
        decode never stalls on admission (the round-3 head-of-line
        blocking), prefill host work (bucketing, padding) overlaps the
        chunk's device time, and admitted sequences join the next chunk.
        K is fixed, so exactly one decode program compiles for the
        engine's lifetime; per-slot budgets freeze finished slots
        device-side and the host discards EOS/budget overshoot."""
        self._expire_deadlines()
        self._observe_health()
        if not self.active.any():
            # nothing decoding: plain blocking admission
            self._admit()
            if not self.active.any():
                return bool(self._queue)
        if self._spec_mode != "off" and not (
                self._degctl is not None and self._degctl.disable_spec):
            # A verify pass buys accepted+1 tokens per DRAFTING slot
            # for one weight stream, but costs every OTHER active slot
            # its chunk amortization: the pass is one host sync that
            # emits exactly 1 token for a draftless slot, vs max_chunk
            # tokens per sync from the plain chunk below. Preempting
            # the chunk for a single drafting slot would collapse a
            # mixed batch's throughput (7 slots × K tokens/sync → 7 ×
            # 1), so verify only preempts when drafting slots are at
            # least HALF the active set — the regime where the weight-
            # stream amortization outweighs the lost sync amortization.
            # step() keeps the unconditional preempt: there the
            # alternative is a 1-token pass, and verify strictly
            # dominates it. The O(1) eligibility count runs before the
            # O(history) drafter scan: when the gate cannot pass even
            # if every eligible slot proposed, don't pay the scan.
            n_active = int(self.active.sum())
            eligible = sum(
                1 for s in range(self.cfg.max_slots)
                if self.active[s] and self._draft_budget(s) > 0)
            drafts = (self._propose_drafts()
                      if 2 * eligible >= n_active else {})
            if drafts and 2 * len(drafts) >= n_active:
                return self._spec_step(drafts)
            self.spec_stats["fallback_steps"] += 1
            if self._tel is not None:
                self._tel.on_spec_fallback()
        t0 = time.perf_counter()
        tr = self._tracer
        seq = tr.next_step() if tr is not None else 0
        adv = {} if tr is not None and tr.want_step(seq) else None
        occ = float(self.active.sum()) / self.cfg.max_slots
        K = max_chunk
        # capture the chunk's view BEFORE admission: newly admitted
        # slots must not decode mid-chunk (their lengths land at
        # integrate). The OCCUPANTS are captured too: the overlapped
        # admission may PREEMPT a slot and re-claim it in the same
        # tick, and the chunk's tokens must never credit the new
        # occupant (identity-checked in the sync loop below)
        chunk_slots = self.active.copy()
        chunk_reqs = {s: self._slot_req[s]
                      for s in range(self.cfg.max_slots)
                      if chunk_slots[s]}
        p_dec = None
        try:
            self._fault_point("decode_chunk")
            self._cow_for_decode(K)
            budget = self._slot_budgets()
            use_samp, samp = self._slot_sampling()
            self._key, sub = jax.random.split(self._key)
            toks = jnp.asarray(self.last_tok[:, None], jnp.int32)
            lens = jnp.asarray(self.seq_lens, jnp.int32)
            act = jnp.asarray(chunk_slots)
            bt = (jnp.asarray(self.pool.block_tables) if self.cfg.paged
                  else jnp.zeros((1,), jnp.int32))
            caches = self.layer_caches if self.cfg.paged else self.caches
            prof = self._prof
            p_want = prof is not None and prof.want("decode_chunk")
            t_call = time.perf_counter()
            with self._ctx():
                toks_all, caches, _ = self._decode_n()(
                    self._pb, toks, caches, lens, act,
                    jnp.asarray(budget), bt, sub, samp, K, use_samp)
            if self.cfg.paged:
                self.layer_caches = caches
            else:
                self.caches = caches
            t_disp = time.perf_counter()
            t_admit0 = t_disp
            if p_want:
                # measured device wall of the chunk itself: blocks on
                # the chunk's outputs BEFORE the overlapped admission
                # dispatch, so the sample is the program, not the
                # dispatch-to-token-sync window sync_wall_ms estimates
                p_dec = prof.observe("decode_chunk", t0, t_call,
                                     t_disp, toks_all)
                self._hbm_update()
                # admit_dispatch_ms must window the ADMISSION work
                # only — the measured device wait above is not it
                t_admit0 = time.perf_counter()
            # admission dispatches behind the in-flight chunk (stream
            # order: chunk → prefills → inserts into the chunk's
            # output caches)
            pending = self._admit_dispatch()
            t_admit = time.perf_counter()
            toks_np = np.asarray(toks_all)  # ONE sync for K tokens
        except BaseException as e:
            if not self._recoverable(e):
                raise
            # quarantine: the chunk's host state never advanced (the
            # sync above is where tokens would have landed), so the
            # chunk's participants replay; an un-synced but dispatched
            # chunk re-runs over the same positions bit-identically
            self._recover_step(e, chunk_slots, "decode_chunk")
            return True
        # TPOT window closes at the chunk's token sync — before the
        # admitted requests' first-token syncs in _admit_integrate, so
        # loaded chunks report decode latency, not admission latency
        # (matches what step() measures)
        t_sync = time.perf_counter()
        emitted = 0
        cost_by_slot: Dict[int, list] = {} if self._cost_enabled \
            else None
        for k in range(K):
            for slot in range(self.cfg.max_slots):
                # the slot advances only while its DISPATCH-TIME
                # occupant still owns it: gone = finished (EOS) at an
                # earlier k of this same chunk; replaced = preempted
                # mid-chunk and re-claimed by this tick's admission —
                # either way the chunk's remaining tokens are
                # discarded, exactly like cancel's
                req = chunk_reqs.get(slot)
                if (req is None or k >= budget[slot]
                        or self._slot_req.get(slot) is not req):
                    continue
                tok = int(toks_np[k, slot])
                req.output.append(tok)
                self.seq_lens[slot] += 1
                self.last_tok[slot] = tok
                emitted += 1
                if adv is not None:
                    adv[req.rid] = adv.get(req.rid, 0) + 1
                if cost_by_slot is not None:
                    cost_by_slot.setdefault(slot, [req, 0])[1] += 1
                self._maybe_finish(slot, tok)
        self._tokens_emitted += emitted
        if cost_by_slot:
            self._attribute_cost(
                "decode_chunk",
                p_dec["device_ms"] if p_dec is not None
                else (t_sync - t_disp) * 1e3,
                p_dec is not None,
                [(req, n) for req, n in cost_by_slot.values()])
        if adv is not None:
            # sampled: measured decomposition. Unsampled fallback:
            # same schedule/dispatch windows, plus sync_wall_ms
            # (dispatch-done -> token sync HOST wall) with
            # admit_dispatch_ms reported separately — host admission
            # work OVERLAPPING that window, subtractable when a
            # first-time compile lands in admission
            timing = (dict(p_dec, profiled=True) if p_dec is not None
                      else {"schedule_ms": (t_call - t0) * 1e3,
                            "dispatch_ms": (t_disp - t_call) * 1e3,
                            "sync_wall_ms": (t_sync - t_disp) * 1e3})
            tr.step(seq, "decode_chunk", t0, time.perf_counter(),
                    occupancy=occ, tokens_advanced=emitted,
                    chunk_budget_spent=K, advanced=adv,
                    admit_dispatch_ms=(t_admit - t_admit0) * 1e3,
                    **timing)
        self._integrate_guarded(pending, "chunk_integrate")
        if self._tel is not None:
            self._tel.on_tokens(emitted, (t_sync - t0) * 1e3)
            self._tel.on_state(*self._tel_state())
        return True

    def step_adaptive(self, max_chunk: int = 8,
                      probe_chunk: int = 2) -> bool:
        """``step_chunk`` with load-adaptive granularity.

        The fixed-K chunk is a TTFT/throughput tradeoff: admission
        dispatches behind the in-flight chunk, so a request that arrives
        at a chunk boundary waits ~K decode steps of device time before
        its prefill runs (the round-5 load curve measured that cost at
        ~70 ms p50 at mid-load for K=8, where per-token admission beat
        the chunked loop). This scheduler keeps full chunks only in
        steady-state decode and drops to ``probe_chunk`` whenever
        admission work is queued — short chunks reach the next admission
        point sooner AND notice freed slots sooner, while an empty queue
        costs nothing. K is static to the compiled program, so at most
        two decode programs compile for the engine's lifetime (compile
        both up front by running a short ``max_chunk=probe_chunk``
        request through the engine before serving).

        Short chunks pay off when admission can happen SOON: a free
        slot now, or an active slot whose remaining budget ends inside
        this chunk (the chunk-boundary sync is what detects EOS/budget
        completion — a full chunk makes a queued request wait up to
        K-1 frozen steps behind a slot that finished at step 0). When
        every slot is busy with long remaining budgets, full chunks
        win: each boundary sync costs a host round-trip (~85 ms
        through the remote-TPU tunnel) and buys nothing.

        Degradation (throttle level): forced to ``probe_chunk`` — an
        already-compiled program, so shrinking the chunk budget under
        pressure never triggers a new jit specialization."""
        k = max_chunk
        if self._degctl is not None and self._degctl.throttle:
            k = min(probe_chunk, max_chunk)
        elif self._queue:
            if not self.active.all():
                k = min(probe_chunk, max_chunk)
            else:
                budgets = self._slot_budgets()
                soonest = min(
                    (budgets[s] for s in range(self.cfg.max_slots)
                     if self.active[s]), default=max_chunk + 1)
                if soonest <= max_chunk:
                    k = min(probe_chunk, max_chunk)
        return self.step_chunk(k)

    def run(self, prompts: Sequence, max_new_tokens: int = 32,
            eos_token_id: Optional[int] = None,
            max_chunk: int = 8) -> List[Request]:
        """Submit all prompts, drive until completion, return Requests
        in submission order (each carries .output and .ttft_ms).

        Drives ``step_chunk`` so decode syncs the host once per
        ``max_chunk`` tokens; admission (prefill) happens between chunks
        while the previous chunk's tokens are being consumed."""
        rids = [self.add_request(p, max_new_tokens, eos_token_id)
                for p in prompts]
        while self.step_chunk(max_chunk) or self._queue or \
                self.active.any():
            if self._draining and not self.active.any():
                break  # drained mid-run: queued requests stay queued
        return [self._finished[r] for r in rids if r in self._finished]

    # ---------------- telemetry ----------------
    def _tel_state(self):
        """(queue_depth, occupancy, kv_used, kv_total) — all host-side
        scheduler state, no device traffic. Thread-note: also called
        from the /healthz scrape thread; ``pages_of`` has fixed slot
        keys (created once in PagePool.__init__, values replaced whole
        on free), so concurrent iteration never sees a resized dict —
        a scrape racing the scheduler can read a momentarily stale
        count, which is acceptable for a gauge."""
        if self._san is not None:
            self._san.check_read("_tel_state")
        occ = float(self.active.sum()) / self.cfg.max_slots
        if self.cfg.paged:
            used = float(sum(
                len(self.pool.pages_of[s])
                for s in range(self.pool.slots)))
            total = used + self.pool.free_pages
        else:
            used = float(self.seq_lens[self.active].sum())
            total = float(self.cfg.max_slots * self.cfg.max_len)
        return len(self._queue), occ, used, total

    def metrics_snapshot(self) -> dict:
        """ONE unified serving document: registry aggregates (TTFT/TPOT
        percentiles, queue depth, occupancy, KV utilization, counters —
        when telemetry is on) plus the host-side prefix-cache, spec-
        decode and SLO sub-snapshots, which are ALWAYS present (plain
        host counters survive ``PT_FLAGS_telemetry=off``). Bench ledger
        lines and the dump CLI read this one call instead of stitching
        ``prefix_snapshot`` + ``spec_snapshot`` + ``slo_snapshot``."""
        if self._san is not None:
            self._san.check_read("metrics_snapshot")
        if self._tel is None:
            snap = {"telemetry": "off"}
        else:
            # refresh point-in-time gauges so an idle engine still
            # reports its current state
            self._tel.on_state(*self._tel_state())
            snap = self._tel.snapshot()
        snap["slots"] = {
            "active": int(self.active.sum()),
            "max": self.cfg.max_slots,
        }
        snap["prefix_cache"] = self.prefix_snapshot()
        snap["spec_decode"] = self.spec_snapshot()
        snap["slo"] = self.slo_snapshot()
        # multi-tenant accounting + the admission scheduler's policy
        # name and preemption count ride the one unified document
        snap["tenants"] = self.tenant_snapshot()
        snap["resilience"] = self.resilience_snapshot()
        # program-time attribution (PR 12): measured per-program
        # device ms, watchdog state and HBM residency ride the one
        # unified document too. ONE hbm_accounting walk feeds both
        # the gauges and the snapshot sub-doc.
        hbm = observability.hbm_accounting(self)
        if self._tel is not None:
            self._tel.on_hbm(hbm)
        snap["programs"] = self.profile_snapshot()
        snap["recompile"] = self.recompile_snapshot()
        snap["hbm"] = dict(hbm, total=sum(list(hbm.values())))
        # flight data (PR 13): alert-rule states and per-request
        # device-cost attribution ride the one unified document too
        # (the full time-series stays on timeline_snapshot()/
        # /timeline — windows x samples would bloat every scrape)
        snap["alerts"] = self.alerts_snapshot()
        snap["cost"] = self.cost_snapshot()
        # seal-time contract audit (ptaudit): the self-audit verdict
        # rides the one unified document too ({"enabled": False}
        # when PT_FLAGS_audit_on_seal is off)
        snap["audit"] = self.audit_snapshot()
        return snap

    def prefix_snapshot(self) -> dict:
        """Prefix-cache effectiveness counters (plain host counters —
        available even with PT_FLAGS_telemetry=off, which is how the
        bench A/B reads hit rates)."""
        if self._san is not None:
            self._san.check_read("prefix_snapshot")
        st = {k: v for k, v in list(self.prefix_stats.items())}
        st["enabled"] = self._prefix is not None
        st["cached_blocks"] = (self._prefix.cached_pages
                               if self._prefix is not None else 0)
        tot = st["prompt_tokens"]
        st["hit_rate_tokens"] = (st["hit_tokens"] / tot) if tot else 0.0
        return st

    def spec_snapshot(self) -> dict:
        """Speculative-decoding effectiveness counters (plain host
        counters — available even with PT_FLAGS_telemetry=off, which is
        how the bench A/B reads acceptance rates)."""
        if self._san is not None:
            self._san.check_read("spec_snapshot")
        st = {k: v for k, v in list(self.spec_stats.items())}
        st["enabled"] = self._spec_mode != "off"
        st["mode"] = self._spec_mode
        st["k"] = self.cfg.spec_k
        st["acceptance_rate"] = (st["accepted"] / st["proposed"]
                                 if st["proposed"] else 0.0)
        return st

    def slo_snapshot(self) -> dict:
        """SLO attainment per class + overall goodput (plain host
        counters — available even with PT_FLAGS_telemetry=off, which is
        how the bench goodput sweep reads them). ``goodput`` is
        met / (met + violated) over SLO-tracked finishes; cancelled
        requests are counted separately, never as violations."""
        if self._san is not None:
            self._san.check_read("slo_snapshot")
        classes = {}
        met = violated = 0
        # list(): slo_stats grows a key on a class's FIRST finish, and
        # this runs on the /healthz scrape thread too — iterating the
        # live dict would race the scheduler with RuntimeError
        for cls, st in list(self.slo_stats.items()):
            d = {k: v for k, v in list(st.items())}
            # derive ONLY from the copy: mixing d with the live st
            # could report met=5 next to a goodput computed at met=6
            tracked = d["met"] + d["violated"]
            d["goodput"] = d["met"] / tracked if tracked else None
            classes[cls] = d
            met += d["met"]
            violated += d["violated"]
        tracked = met + violated
        return {
            "classes": classes,
            "met": met,
            "violated": violated,
            "goodput": met / tracked if tracked else None,
        }

    def tenant_snapshot(self) -> dict:
        """Per-tenant serving state: cumulative host counters
        (finished/cancelled/timeouts/failed, tokens, attributed
        device-ms, SLO met/violated, preemptions) joined with LIVE
        usage — active slots, held KV pages, queued requests — the
        isolation numbers the multi-tenant scheduler's quotas act on.
        Plain host counters, available with telemetry off; copy-on-
        read like every scrape surface (tenant ``"-"`` is untagged
        traffic)."""
        if self._san is not None:
            self._san.check_read("tenant_snapshot")
        tenants: Dict[str, dict] = {}

        def bucket(key):
            d = tenants.get(key)
            if d is None:
                d = tenants[key] = {
                    "active_slots": 0, "pages": 0, "queued": 0}
            return d

        for key, st in list(self.tenant_stats.items()):
            bucket(key).update({k: v for k, v in list(st.items())})
        for slot, req in list(self._slot_req.items()):
            d = bucket(req.tenant or "-")
            d["active_slots"] += 1
            if self.cfg.paged:
                # pages_of values are replaced whole on free — the
                # same staleness contract as _tel_state's gauge read
                d["pages"] += len(self.pool.pages_of[slot])
        for req in list(self._queue):
            bucket(req.tenant or "-")["queued"] += 1
        return {
            "tenants": tenants,
            "scheduler": {k: v
                          for k, v in list(self.sched_stats.items())},
        }

    def slo_window_reset(self):
        """Zero the host-side SLO counters — one measurement window per
        load step in a goodput sweep (registry counters keep their
        cumulative totals, same contract as metrics_window_reset)."""
        self.slo_stats = {}

    def backpressure(self) -> dict:
        """Honest admission readiness for ``/healthz``: queue depth,
        free slots/pages and whether admission is SATURATED (requests
        waiting with zero free slots) — the state a router drains a
        replica on. Host scheduler state only; safe from the scrape
        thread (same staleness contract as ``_tel_state``)."""
        if self._san is not None:
            self._san.check_read("backpressure")
        qd = len(self._queue)
        free = len(self._free_heap)
        ctl = self._degctl
        out = {
            "queue_depth": qd,
            "free_slots": free,
            "occupancy": float(self.active.sum()) / self.cfg.max_slots,
            # two saturation modes: no free slot, or — the PAGED
            # engine's dominant stall — slots free but the last
            # admission pass blocked on KV-pool pages
            "saturated": qd > 0 and (free == 0 or self._pool_blocked),
            # resilience bits a router steers on: draining (stop
            # sending, we're shutting down) and the degradation ladder
            "draining": self._draining,
            "degraded": ctl.degraded if ctl is not None else False,
            "degradation_level": ctl.level if ctl is not None else 0,
        }
        if self.cfg.paged:
            out["free_pages"] = self.pool.free_pages
            out["pool_blocked"] = self._pool_blocked
        return out

    def metrics_window_reset(self):
        """Reset percentile windows + peak trackers (cumulative
        counters keep running) — one measurement window per benchmark
        sweep."""
        if self._tel is not None:
            self._tel.window_reset()

    # ---------------- per-request device-cost attribution ----------
    def _attribute_cost(self, program: str, device_ms: float,
                        profiled: bool, shares):
        """Split one step's device wall across the requests it
        advanced, proportional to tokens advanced (``shares`` is
        [(req, tokens)]). The split is exact up to float rounding —
        the shares sum to ``device_ms`` — which is the documented
        rounding the reconciliation test allows. ``profiled`` marks a
        MEASURED sample (block_until_ready device wall); the fallback
        is the step's sync-wall estimate, accumulated separately so a
        reader can tell evidence from upper bound."""
        if device_ms <= 0 or not shares:
            return
        total = sum(n for _, n in shares)
        if total <= 0:
            return
        st = self.cost_stats
        st["attributed_ms"][program] = \
            st["attributed_ms"].get(program, 0.0) + device_ms
        st["profiled_ms" if profiled else "estimated_ms"] += device_ms
        for req, n in shares:
            share = device_ms * (n / total)
            req.device_ms += share
            if profiled:
                req.device_ms_profiled += share

    def _record_cost_finish(self, req: Request):
        """Terminal cost bookkeeping for one request (idempotent —
        terminal paths can revisit a request across flush points)."""
        if not self._cost_enabled or req._cost_recorded:
            return
        req._cost_recorded = True
        st = self.cost_stats
        st["requests_finished"] += 1
        st["request_device_ms_total"] += req.device_ms
        key = req.slo or "untracked"
        by = st["by_slo"].get(key)
        if by is None:
            by = st["by_slo"][key] = {"requests": 0,
                                      "device_ms_total": 0.0}
        by["requests"] += 1
        by["device_ms_total"] += req.device_ms
        # per-tenant attributed cost rides the same finish record
        # (cost-gated like cost_stats: off = requests carry 0 anyway)
        self._tenant_bucket(req.tenant)["device_ms"] += req.device_ms
        self._cost_window.append(req.device_ms)
        if self._tel is not None:
            self._tel.on_request_cost(key, req.device_ms,
                                      tenant=req.tenant or "-")

    def _flush_cost(self):
        """Record finish-time costs deferred past the step's
        attribution pass (requests that hit EOS/budget mid-step must
        include the final chunk's share — _maybe_finish runs BEFORE
        the step attributes, so it defers here)."""
        if not self._cost_pending:
            return
        pending, self._cost_pending = self._cost_pending, []
        for req in pending:
            self._record_cost_finish(req)

    def cost_snapshot(self) -> dict:
        """Per-request device-cost attribution totals (plain host
        counters — available with PT_FLAGS_telemetry=off, like every
        other serving stat surface). ``request_device_ms_p50`` is over
        the recent finished-request window."""
        if self._san is not None:
            self._san.check_read("cost_snapshot")
        if not self._cost_enabled:
            return {"enabled": False}
        st = {k: v for k, v in list(self.cost_stats.items())}
        st["attributed_ms"] = {
            k: v for k, v in list(st["attributed_ms"].items())}
        st["by_slo"] = {k: {kk: vv for kk, vv in list(v.items())}
                        for k, v in list(st["by_slo"].items())}
        win = sorted(self._cost_window)
        st["request_device_ms_p50"] = (win[len(win) // 2] if win
                                       else None)
        n = st["requests_finished"]
        st["request_device_ms_mean"] = (
            st["request_device_ms_total"] / n if n else None)
        st["enabled"] = True
        return st

    # ---------------- flight data (time-series + alerts) ----------
    def _flight_tick(self):
        """One scheduler tick for the flight-data layer: advance the
        time-series store (a window closes every cadence-th tick) and,
        on a closed window, run the alert detectors over the series.
        Pure host bookkeeping; the tick count is the only input to
        every decision."""
        ts = self._ts
        if ts is None:
            return
        sample = ts.on_tick(self._flight_collect)
        if sample is not None and self._alerts is not None:
            self._alerts.evaluate(ts)

    def _flight_collect(self) -> dict:
        """Cumulative counters + point gauges for one time-series
        window (scheduler-thread only — the store's readers are the
        scrape-safe surface). Host values the scheduler already holds;
        histogram window-percentiles ride along when telemetry is
        on."""
        st = self.resilience_stats
        counters = {
            "tokens": float(self._tokens_emitted),
            "finished": float(len(self._finished)),
            "prefix_hits": float(self.prefix_stats["hits"]),
            "prefix_misses": float(self.prefix_stats["misses"]),
            "prefix_hit_tokens": float(
                self.prefix_stats["hit_tokens"]),
            "prefix_prompt_tokens": float(
                self.prefix_stats["prompt_tokens"]),
            "prefix_evictions": float(self.prefix_stats["evictions"]),
            "spec_proposed": float(self.spec_stats["proposed"]),
            "spec_accepted": float(self.spec_stats["accepted"]),
            "spec_verify_calls": float(
                self.spec_stats["verify_calls"]),
            "recoveries": float(st["recoveries"]),
            "retries": float(st["retries"]),
            "timeouts": float(st["timeouts"]),
            "failed": float(st["failed"]),
            "recompiles": float(
                sum(self._watchdog.recompiles.values())
                if self._watchdog is not None else 0),
            "device_ms": float(self.cost_stats["profiled_ms"]
                               + self.cost_stats["estimated_ms"]),
        }
        for cls, s in list(self.slo_stats.items()):
            counters[f"slo_met:{cls}"] = float(s["met"])
            counters[f"slo_violated:{cls}"] = float(s["violated"])
        qd, occ, used, total = self._tel_state()
        ctl = self._degctl
        gauges = {
            "queue_depth": float(qd),
            "occupancy": occ,
            "active_slots": float(self.active.sum()),
            "free_slots": float(len(self._free_heap)),
            "kv_used": used,
            "kv_total": total,
            "kv_utilization": used / total if total else 0.0,
            "degradation_level": float(ctl.level
                                       if ctl is not None else 0),
        }
        percentiles = (self._tel.window_percentiles()
                       if self._tel is not None else {})
        return {"counters": counters, "gauges": gauges,
                "percentiles": percentiles}

    def timeline_snapshot(self) -> dict:
        """The retained time-series windows (``{"enabled": False}``
        when PT_FLAGS_timeseries is off). Copy-on-read — the
        /timeline endpoint and `dump --timeline` read this from the
        scrape thread."""
        if self._san is not None:
            self._san.check_read("timeline_snapshot")
        if self._ts is None:
            return {"enabled": False}
        st = self._ts.snapshot()
        st["enabled"] = True
        return st

    def alerts_snapshot(self) -> dict:
        """Alert-rule states + bounded transition log
        (``{"enabled": False}`` when alerts are off). Copy-on-read."""
        if self._san is not None:
            self._san.check_read("alerts_snapshot")
        if self._alerts is None:
            return {"enabled": False}
        st = self._alerts.snapshot()
        st["enabled"] = True
        return st

    def alerts_window_reset(self):
        """Zero the per-rule peak trackers — one measurement window
        per bench sweep step (fire counts, hysteresis state and the
        registry totals keep running)."""
        if self._alerts is not None:
            self._alerts.window_reset()

    # ---------------- program-time attribution ----------------
    def _hbm_update(self):
        """Refresh the HBM residency gauges + watermarks from the
        pools the engine owns (array nbytes metadata — no device
        traffic). Called at init, on profiler-sampled steps and from
        metrics_snapshot; host-side numbers via ``hbm_snapshot``."""
        if self._tel is not None:
            self._tel.on_hbm(observability.hbm_accounting(self))

    def hbm_snapshot(self) -> dict:
        """Live HBM residency by component (kv_pool, kv_scales,
        weights_<dtype>, prefix_store) — plain host metadata,
        available even with PT_FLAGS_telemetry=off."""
        if self._san is not None:
            self._san.check_read("hbm_snapshot")
        st = observability.hbm_accounting(self)
        st["total"] = sum(list(st.values()))
        return st

    def profile_snapshot(self) -> dict:
        """Measured per-program device-time stats (PT_FLAGS_
        profile_programs; ``{"enabled": False}`` when off). Host
        counters — available even with PT_FLAGS_telemetry=off."""
        if self._san is not None:
            self._san.check_read("profile_snapshot")
        if self._prof is None:
            return {"enabled": False}
        st = self._prof.snapshot()
        st["enabled"] = True
        return st

    def recompile_snapshot(self) -> dict:
        """Recompile-watchdog state (sealed bit, per-program post-seal
        recompile counts; ``{"enabled": False}`` when off)."""
        if self._san is not None:
            self._san.check_read("recompile_snapshot")
        if self._watchdog is None:
            return {"enabled": False}
        return self._watchdog.snapshot()

    def profile_window_reset(self):
        """Zero the profiler's host-side stats — one measurement
        window per bench sweep (registry histogram totals keep
        running, like metrics_window_reset)."""
        if self._prof is not None:
            self._prof.window_reset()

    def seal_programs(self):
        """Seal the recompile watchdog's expected program set NOW
        (e.g. right after a bench warmup) instead of waiting out
        PT_FLAGS_recompile_warmup_ticks. No-op when the watchdog is
        off. With ``PT_FLAGS_audit_on_seal`` the sealed program set is
        also contract-audited (ptaudit AL/DQ/TX/DD) at this engine's
        own shapes — trace-only, compile accounting untouched."""
        if self._watchdog is not None:
            self._watchdog.seal()
        if self._audit_on_seal:
            from ..analysis.program_audit import audit_engine

            try:
                self._audit_report = audit_engine(self, arm="seal")
            except Exception as e:
                # the self-audit NEVER takes down a production seal
                # (the recompile watchdog's "never raises" contract):
                # probe/signature drift surfaces as an error verdict
                # on the snapshot instead
                self._audit_report = {
                    "arm": "seal", "programs": {}, "skipped": {},
                    "violations": [], "error": f"{type(e).__name__}: "
                                               f"{e}"}

    def audit_snapshot(self) -> dict:
        """Seal-time contract-audit verdict (``{"enabled": False}``
        when PT_FLAGS_audit_on_seal is off; ``sealed: False`` before
        the first seal). Copy-on-read like every scrape surface —
        the report is immutable after seal, and only copies leave."""
        if self._san is not None:
            self._san.check_read("audit_snapshot")
        if not self._audit_on_seal:
            return {"enabled": False}
        rep = self._audit_report
        if rep is None:
            return {"enabled": True, "sealed": False}
        out = {
            "enabled": True, "sealed": True,
            "programs": len(list(rep["programs"])),
            "skipped": len(list(rep["skipped"])),
            "violations": [
                {"program": v.program, "rule": v.rule,
                 "message": v.message}
                for v in list(rep["violations"])],
        }
        if rep.get("error"):
            out["error"] = rep["error"]
        return out

    def prefix_affinity_tokens(self, hashes: List[bytes]) -> int:
        """Read-only prefix-affinity probe for the multi-engine
        router: how many leading prompt tokens of the rolling
        block-hash chain this engine's prefix store already holds.
        Pure peek — no LRU refresh, no adoption, no device traffic —
        so probing every replica before routing perturbs none of
        them. 0 when the store is off or degradation disabled it
        (min_service: adoption wouldn't happen anyway, so affinity
        must not steer traffic at pages the replica won't share)."""
        if self._prefix is None or self._prefix_disabled():
            return 0
        return self._prefix.match_len(hashes) * self._prefix_block


# ---------------------------------------------------------------------------
# /metrics + /healthz exposition (parity: FastDeploy-style serving
# endpoints; scrape target for Prometheus)
# ---------------------------------------------------------------------------
class MetricsServer:
    """Handle for a running metrics endpoint: ``server_address`` for
    the bound port and a CLEAN ``shutdown()`` — stop ``serve_forever``,
    JOIN the serving thread, CLOSE the listening socket — so chaos
    tests and multi-engine runs don't leak listeners or fds.
    Idempotent; also a context manager."""

    def __init__(self, server, thread):
        self._server = server
        self._thread = thread
        self._closed = False

    @property
    def server_address(self):
        return self._server.server_address

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._thread.join(timeout=10)
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


def metrics_http_get(engine, path: str):
    """Route one GET against the serving observability surface —
    ``/metrics`` (Prometheus text), ``/healthz`` (JSON readiness, 503
    while saturated/draining), ``/trace`` (Chrome trace JSON,
    ``?fleet=1`` merges a router's fleet), ``/timeline`` (retained
    time-series windows). Returns ``(status, body_bytes, content_type)``
    or ``None`` for an unknown path.

    Factored out of :func:`start_metrics_server` so the streaming API
    front door (``paddle_tpu.serving_api``) serves the SAME
    observability endpoints beside ``/v1/*`` instead of duplicating
    them. ``engine`` may be an engine, an ``EngineRouter``, or None."""
    import json

    bare = path.split("?")[0]
    if bare == "/metrics":
        text = observability.global_registry().prometheus_text()
        return (200, text.encode(),
                "text/plain; version=0.0.4; charset=utf-8")
    if bare == "/healthz":
        payload = {"status": "ok",
                   "telemetry": observability.enabled()}
        code = 200
        if engine is not None:
            bp = engine.backpressure()
            payload["backpressure"] = bp
            payload["engine"] = engine.metrics_snapshot()
            # degraded is NOT a readiness failure: the replica still
            # serves (shed/throttled) — a router reads the bit to
            # deprioritize it, and the numeric RUNG to rank replicas
            # (a shed_batch replica beats a min_service one)
            payload["degraded"] = bool(bp.get("degraded"))
            payload["degradation_level"] = int(
                bp.get("degradation_level", 0))
            if bp.get("draining"):
                # drain() in progress: in-flight requests still
                # complete, but a router must stop sending —
                # readiness fails first
                payload["status"] = "draining"
                code = 503
            elif bp["saturated"]:
                # honest readiness: requests are waiting and no slot
                # can take them — tell the router to drain, don't
                # smile through it
                payload["status"] = "saturated"
                code = 503
        return (code, json.dumps(payload, default=str).encode(),
                "application/json")
    if bare == "/timeline":
        tl = getattr(engine, "timeline_snapshot", None)
        snap = tl() if tl is not None else None
        if snap is None or not snap.get("enabled"):
            return (404, b"timeline disabled (PT_FLAGS_timeseries "
                    b"off)", "text/plain")
        return (200, json.dumps(snap, default=str).encode(),
                "application/json")
    if bare == "/trace":
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(path).query)
        want_fleet = q.get("fleet", ["0"])[0] in ("1", "true")
        tracer = getattr(engine, "_tracer", None)
        if want_fleet and hasattr(engine, "_replicas"):
            # /trace?fleet=1 on a router: ONE merged Perfetto
            # document — router + every replica tracer, failed-over
            # rids joined by flow events (tracing.fleet_chrome_trace)
            body = json.dumps(
                observability.tracing.fleet_chrome_trace(engine),
                default=str).encode()
            return (200, body, "application/json")
        if tracer is None:
            return (404, b"tracing disabled (telemetry off or "
                    b"trace_sample=0)", "text/plain")
        body = json.dumps(
            observability.tracing.chrome_trace([tracer]),
            default=str).encode()
        return (200, body, "application/json")
    return None


def start_metrics_server(engine: Optional[ContinuousBatchingEngine] = None,
                         host: str = "127.0.0.1", port: int = 0):
    """Serve ``/metrics`` (Prometheus text exposition of the process
    registry), ``/healthz`` (JSON readiness: liveness + engine snapshot
    + back-pressure state — **503** while admission is saturated or
    the engine is draining, so a router can drain the replica),
    ``/trace`` (the engine's lifecycle tracer as Chrome trace-event
    JSON, Perfetto-loadable; 404 when tracing is off) and
    ``/timeline`` (the engine's/router's retained time-series windows
    as JSON; 404 when ``PT_FLAGS_timeseries`` is off) on a daemon
    thread.

    Also accepts an :class:`~paddle_tpu.inference.router.EngineRouter`
    as ``engine``: the router exposes the same ``backpressure()`` /
    ``metrics_snapshot()`` surface, so ``/healthz`` becomes the
    FLEET-aggregate readiness (503 only when no replica can take
    traffic) and ``/trace`` serves the router's route/failover/breaker
    event stream. Returns a :class:`MetricsServer` handle; read
    ``handle.server_address`` for the bound port (``port=0`` picks a
    free one), call ``handle.shutdown()`` for a clean stop (thread
    joined, socket closed)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def _send(self, code, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            # a scrape must never die on a transient error: the
            # liveness endpoint failing under load defeats its purpose
            try:
                routed = metrics_http_get(engine, self.path)
                if routed is None:
                    self._send(404, b"not found", "text/plain")
                else:
                    self._send(*routed)
            except BrokenPipeError:
                pass
            except Exception as e:  # noqa: BLE001
                try:
                    self._send(500, repr(e).encode(), "text/plain")
                except Exception:
                    pass

        def log_message(self, fmt, *args):  # quiet scrape noise
            pass

    server = ThreadingHTTPServer((host, port), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="pt-metrics-server")
    thread.start()
    return MetricsServer(server, thread)
