"""Shared-prefix KV store for the continuous-batching engine.

Production prompt traffic is dominated by shared system prompts and
few-shot templates: the cheapest prefill FLOPs and HBM bytes are the
ones a prefix cache lets the engine skip entirely. Prefixes are keyed
by a ROLLING HASH over fixed-size prompt-token blocks — block i's
digest chains block i-1's, so one dict lookup per block walks the
longest cached block-aligned prefix without storing per-prompt keys.

Two stores, one per KV-cache mode:

- ``PagedPrefixStore`` (paged mode) maps digest → PAGE ID. The store
  owns a refcount on each cached page (``PagePool.retain``); admission
  shares matched pages straight into the new slot's block table
  (``PagePool.adopt`` — zero copies), and the engine copy-on-writes any
  shared page before a write can touch it. Eviction is LRU over
  entries whose page refcount is 1 (cache-only — nothing borrowed by a
  live slot), triggered by pool pressure.

- ``ContigPrefixStore`` (contiguous mode) maps digest → the block's
  actual K/V rows, stacked over layers ``[n_layers, block, kvh, d]``
  (device arrays in the cache dtype). Slots have private rows, so a
  hit COPIES the cached blocks in (one small compiled insert per
  block) — recompute is saved, memory is not shared. Eviction is LRU
  over a block-count cap (entries are never borrowed: refcount-0 by
  construction).

Host-side bookkeeping only: O(prompt blocks) python per admission,
never inside a compiled program.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

_SEED = b"pt-prefix-v1"


def block_hashes(prompt: np.ndarray, block: int,
                 namespace: str = "") -> List[bytes]:
    """Chained digests of the prompt's FULL token blocks (the rolling
    hash): ``h_i = H(h_{i-1} || tokens[i*B:(i+1)*B])``. The partial
    tail block is never hashed — prefixes are block-aligned.

    ``namespace`` seeds the chain (multi-tenant isolation): two tenants
    submitting the SAME system prompt get disjoint digest chains, so
    neither can probe for — or borrow — the other's cached KV. The
    default empty namespace reproduces the un-namespaced chain bit for
    bit (single-tenant traffic is unchanged)."""
    toks = np.ascontiguousarray(np.asarray(prompt).reshape(-1), np.int64)
    out: List[bytes] = []
    prev = _SEED + namespace.encode() if namespace else _SEED
    for i in range(toks.size // block):
        h = hashlib.blake2b(
            prev + toks[i * block:(i + 1) * block].tobytes(),
            digest_size=16).digest()
        out.append(h)
        prev = h
    return out


class PagedPrefixStore:
    """digest → page id, refcount-pinned in the engine's PagePool.

    Entries remember the NAMESPACE (tenant) that published them, so
    pool-pressure eviction can spend a tenant's own cold entries first
    (``evict(prefer_ns=...)``) — one tenant's eviction storm drains its
    own namespace before it can touch another tenant's shared system
    prompt."""

    def __init__(self):
        # LRU order == dict order: least-recent first.
        # digest -> (page id, namespace)
        self._blocks: "OrderedDict[bytes, Tuple[int, str]]" = \
            OrderedDict()
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._blocks)

    def __contains__(self, digest) -> bool:
        return digest in self._blocks

    @property
    def cached_pages(self) -> int:
        return len(self._blocks)

    def match(self, hashes: List[bytes]) -> List[int]:
        """Longest cached prefix: pages of the leading present blocks
        (LRU-refreshed)."""
        pages = []
        for h in hashes:
            ent = self._blocks.get(h)
            if ent is None:
                break
            self._blocks.move_to_end(h)
            pages.append(ent[0])
        return pages

    def match_len(self, hashes: List[bytes]) -> int:
        """Read-only peek at the longest cached prefix length (in
        blocks) — NO LRU refresh, so a router probing every replica's
        store for prefix affinity perturbs none of their eviction
        orders. GIL-atomic membership tests only: safe to call off
        the scheduler thread."""
        n = 0
        for h in hashes:
            if h not in self._blocks:
                break
            n += 1
        return n

    def insert(self, digest: bytes, page: int, pool,
               ns: str = "") -> bool:
        """Pin ``page`` under ``digest`` (no-op if already cached —
        the original stays authoritative). ``ns`` records the
        publishing namespace for eviction preference."""
        if digest in self._blocks:
            self._blocks.move_to_end(digest)
            return False
        pool.retain(page)
        self._blocks[digest] = (page, ns)
        return True

    def evictable_pages(self, pool, exclude=()) -> int:
        """How many pages ``evict`` could free right now: entries
        nothing but the store owns, minus ``exclude`` (pages the
        caller is about to adopt, which would pin them)."""
        ex = set(exclude)
        return sum(1 for p, _ns in self._blocks.values()
                   if p not in ex and pool.ref.get(p, 0) == 1)

    def evict(self, pool, n_pages: int,
              prefer_ns: Optional[str] = None) -> int:
        """Free up to ``n_pages`` pages, LRU-first, skipping entries a
        live slot is still borrowing (page refcount > 1). Evicting a
        chain-interior block strands its (unreachable) children until
        their own LRU turn — correctness is unaffected, lookups just
        stop at the gap.

        ``prefer_ns``: spend THAT namespace's cold entries first (the
        requesting tenant paying for its own pressure); only when its
        namespace can't cover the shortfall does eviction fall back to
        global LRU over the rest."""
        freed = 0
        passes = ([prefer_ns, None] if prefer_ns is not None
                  else [None])
        for want_ns in passes:
            if freed >= n_pages:
                break
            for digest, (page, ns) in list(self._blocks.items()):
                if freed >= n_pages:
                    break
                if want_ns is not None and ns != want_ns:
                    continue
                if pool.ref.get(page, 0) != 1:
                    continue  # borrowed by an active slot
                del self._blocks[digest]
                pool.release(page)
                self.evictions += 1
                freed += 1
        return freed


class ContigPrefixStore:
    """digest → materialized K/V block rows (device arrays)."""

    def __init__(self, max_blocks: int):
        self.max_blocks = max(int(max_blocks), 0)
        # digest -> (k, v, ns); k/v: [n_layers, block, kvh, d].
        # LRU order == dict order: least-recent first.
        self._blocks: "OrderedDict[bytes, Tuple]" = OrderedDict()
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._blocks)

    def __contains__(self, digest) -> bool:
        return digest in self._blocks

    @property
    def cached_pages(self) -> int:
        return len(self._blocks)

    def match(self, hashes: List[bytes]) -> List[Tuple]:
        out = []
        for h in hashes:
            ent = self._blocks.get(h)
            if ent is None:
                break
            self._blocks.move_to_end(h)
            out.append(ent[:2])
        return out

    def match_len(self, hashes: List[bytes]) -> int:
        """Read-only peek (see ``PagedPrefixStore.match_len``)."""
        n = 0
        for h in hashes:
            if h not in self._blocks:
                break
            n += 1
        return n

    def insert(self, digest: bytes, k, v, ns: str = "",
               protect=()) -> bool:
        """``protect``: digests of the chain currently being inserted —
        eviction must not cannibalize the chain's own earlier blocks
        (evicting block 0 to make room for block 1 would leave a gap
        every later lookup stops at)."""
        if self.max_blocks == 0:
            return False
        if digest in self._blocks:
            self._blocks.move_to_end(digest)
            return False
        keep = set(protect)
        while len(self._blocks) >= self.max_blocks:
            # the inserting namespace's own cold entries go first —
            # a tenant filling the store evicts itself before it can
            # flush a neighbor's cached system prompt; fall back to
            # global LRU, then (degenerate: everything protected) to
            # the raw LRU head
            victim = next(
                (h for h, ent in self._blocks.items()
                 if ent[2] == ns and h not in keep), None)
            if victim is None:
                victim = next(
                    (h for h in self._blocks if h not in keep), None)
            if victim is None:
                victim = next(iter(self._blocks))
            del self._blocks[victim]
            self.evictions += 1
        self._blocks[digest] = (k, v, ns)
        return True
