"""Multi-engine front door: health-routed replicated serving with
cross-replica failover via deterministic ledger replay.

Parity intent: the reference's serving story is fleet-shaped
(``paddle.distributed.launch`` spawning cooperating workers, Fleet
elastic fault tolerance restarting whole ones). PRs 3–10 hardened a
SINGLE replica — continuous batching, paged COW prefix cache, spec
decode, quantized streams, step-level crash recovery, a degradation
ladder, runtime sanitizers. This module goes ABOVE the engine: an
:class:`EngineRouter` owns N :class:`ContinuousBatchingEngine`
replicas (in-process — the same scheduler code a process-per-replica
deployment would run, CPU-testable end to end) and makes the fleet
survive what a single engine cannot: **whole-replica death**.

Three mechanisms, composed:

* **Health-weighted prefix-affinity routing.** Admission hashes the
  prompt with the PR-4 rolling block-hash chain and probes every
  routable replica's prefix store read-only
  (``engine.prefix_affinity_tokens`` — no LRU perturbation): traffic
  sharing a system prompt lands where its pages already live, falling
  back to least-loaded via the honest ``backpressure()`` signals
  (saturation, degradation rung, queue depth). When NO replica is
  routable (all saturated / draining / breaker-open) the router holds
  the request in its OWN queue — fleet-level shedding that composes
  with each replica's PR-7 degradation ladder (deferral, never drop).

* **Per-replica circuit breakers.** closed → open on repeated faults
  in a sliding tick window (or immediately on a whole-replica crash)
  → half-open after a deterministic seeded cooldown (schedule
  multipliers × base cooldown + per-replica seeded jitter — no
  unseeded randomness anywhere, ptlint's DT rules apply) → one canary
  probe tick → closed on success, re-open with the next backoff on
  failure. Open replicas receive no traffic and no ticks.

* **Cross-replica failover by ledger replay.** Every token a replica
  ever emitted lives in the HOST token ledger (the PR-7 crash-recovery
  replay source of truth). When a replica hard-fails (seeded
  ``replica_crash`` / ``replica_hang`` / ``probe_flaky`` injector
  sites at the router's tick seam, or a runtime error escaping the
  engine's own recovery), its in-flight and queued requests are
  RECLAIMED from that ledger and re-admitted on survivors via
  ``request_ledger``/``admit_ledger`` — the surviving replica replays
  prompt+history through its existing ``[slots, C]`` prefill program,
  so greedy outputs stay bit-identical to a fault-free run, the
  ORIGINAL submit/admit instants keep TTFT/SLO accounting honest, and
  zero new programs compile on any replica. The failed replica's
  caches are rebuilt (same shapes — nothing recompiles) so a later
  canary can return it to service empty.

Single-scheduler-thread contract, same as the engine: ONE thread
drives ``step()``/``run()``/``drain()``; ``add_request`` may be
called from producer threads (deque append is atomic, and PLACEMENT —
the submit-to-replica + owner-map write, from either a producer's
``add_request`` or the scheduler's held-queue/failover re-place — is
serialized by a small admission lock, so a failover can never
interleave with a half-finished placement). ``cancel`` of a
router-HELD request is producer-safe too (atomic deque remove);
cancelling a PLACED request delegates to ``engine.cancel``, which
releases slots/pages and therefore shares the engine's
scheduler-thread contract;
``backpressure``/``metrics_snapshot``/``fleet_snapshot`` are
registered copy-on-read scrape readers (sanitizer ``SAFE_READS``,
ptlint CC001–CC003). ``PT_FLAGS_sanitize`` additionally checks the
FLEET invariant once per tick: every rid is owned by exactly one
replica or one queue — the dual-ownership a buggy failover would
create is caught at the tick that caused it.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import flags, observability
from .prefix_cache import block_hashes
from .resilience import (
    FaultInjector,
    InjectedFault,
    RUNTIME_ERRORS,
)
from .serving import (
    ContinuousBatchingEngine,
    EngineConfig,
    Request,
    build_request,
    new_slo_bucket,
    request_ledger,
    request_namespace,
)

# breaker states (also the pt_router_breaker_state gauge encoding)
BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN = 0, 1, 2
BREAKER_NAMES = ("closed", "open", "half_open")


def _parse_schedule(spec) -> List[int]:
    """``PT_FLAGS_router_retry_schedule`` → cooldown multipliers for
    successive breaker opens (last entry repeats)."""
    if isinstance(spec, (list, tuple)):
        vals = [int(v) for v in spec]
    else:
        vals = [int(p) for p in str(spec).split(",") if p.strip()]
    if not vals or any(v < 1 for v in vals):
        raise ValueError(
            f"router retry schedule needs positive multipliers; got "
            f"{spec!r}")
    return vals


class CircuitBreaker:
    """Per-replica breaker, TICK-based for determinism (wall clocks
    would make chaos runs irreproducible — the engine's DT lint rules
    ban them for the same reason).

    closed: faults accumulate in a sliding ``window``-tick log;
    ``trip`` of them open the breaker. ``trip_now`` opens it
    unconditionally (whole-replica crash). open: no traffic, no
    ticks, until ``cooldown × schedule[attempt] + jitter`` ticks pass
    (jitter drawn per-open from a stream seeded on (router seed,
    replica index) — deterministic, mutually isolated). half_open:
    the next tick is a canary probe — ``note_ok`` closes (attempt
    resets), any fault re-opens with the NEXT schedule entry.
    """

    def __init__(self, window: int, trip: int, cooldown: int,
                 schedule: Sequence[int], rng: np.random.Generator):
        for name, v in (("window", window), ("trip", trip),
                        ("cooldown", cooldown)):
            if int(v) < 1:
                raise ValueError(f"breaker {name} must be >= 1; got {v}")
        self.window = int(window)
        self.trip = int(trip)
        self.cooldown = int(cooldown)
        self.schedule = _parse_schedule(schedule)
        self._rng = rng
        self._state = BREAKER_CLOSED
        self._faults: List[int] = []  # tick stamps, window-trimmed
        self._attempt = 0  # consecutive opens (schedule index)
        self.opens = 0  # cumulative (stats)
        self.reopen_at = 0

    # ---------------- views ----------------
    def state(self, tick: int) -> int:
        """Read-only state at ``tick`` (an open breaker READS as
        half-open once its cooldown passed; the transition COMMITS in
        ``advance`` on the scheduler thread — producer-thread routing
        peeks must never mutate)."""
        if self._state == BREAKER_OPEN and tick >= self.reopen_at:
            return BREAKER_HALF_OPEN
        return self._state

    @property
    def name(self) -> str:
        return BREAKER_NAMES[self._state]

    # ---------------- transitions (scheduler thread only) ----------
    def advance(self, tick: int) -> int:
        """Commit the open→half_open transition; returns the state."""
        if self._state == BREAKER_OPEN and tick >= self.reopen_at:
            self._state = BREAKER_HALF_OPEN
        return self._state

    def _open(self, tick: int):
        mult = self.schedule[min(self._attempt, len(self.schedule) - 1)]
        jitter = int(self._rng.integers(0, max(self.cooldown // 2, 1)))
        self.reopen_at = tick + self.cooldown * mult + jitter
        self._state = BREAKER_OPEN
        self._attempt += 1
        self.opens += 1
        self._faults.clear()

    def note_fault(self, tick: int) -> bool:
        """Record one replica fault at ``tick``; True when THIS fault
        opened the breaker (closed with a full window, or a failed
        half-open canary)."""
        if self._state == BREAKER_OPEN:
            return False
        if self._state == BREAKER_HALF_OPEN:
            self._open(tick)  # canary failed: next backoff rung
            return True
        self._faults.append(tick)
        horizon = tick - self.window
        while self._faults and self._faults[0] <= horizon:
            del self._faults[0]
        if len(self._faults) >= self.trip:
            self._open(tick)
            return True
        return False

    def trip_now(self, tick: int) -> bool:
        """Unconditional open (whole-replica crash); True if it was
        not already open."""
        if self._state == BREAKER_OPEN:
            return False
        self._open(tick)
        return True

    def note_ok(self, tick: int):
        """A clean tick: a half-open canary success CLOSES the breaker
        (backoff schedule resets — the replica earned a fresh start);
        closed-state successes just age the fault window."""
        del tick
        if self._state == BREAKER_HALF_OPEN:
            self._state = BREAKER_CLOSED
            self._attempt = 0
            self._faults.clear()

    def snapshot(self, tick: Optional[int] = None) -> dict:
        """Pass the fleet ``tick`` to report the tick-EFFECTIVE state
        (an open breaker past its cooldown reads half-open, matching
        ``backpressure()``'s routing verdict); without it the raw
        committed state could contradict the ``state(tick)`` view in
        the same /healthz document."""
        st = self._state if tick is None else self.state(tick)
        return {
            "state": st,
            "name": BREAKER_NAMES[st],
            "opens": self.opens,
            "attempt": self._attempt,
            "reopen_at": self.reopen_at,
            "window_faults": len(list(self._faults)),
        }


class _Replica:
    """One replica's router-side bookkeeping (the engine itself stays
    oblivious to the fleet)."""

    __slots__ = ("idx", "engine", "breaker", "hung_until", "failovers")

    def __init__(self, idx: int, engine: ContinuousBatchingEngine,
                 breaker: CircuitBreaker):
        self.idx = idx
        self.engine = engine
        self.breaker = breaker
        self.hung_until = 0  # fleet tick a simulated hang ends at
        self.failovers = 0


class EngineRouter:
    """Fleet front door over N continuous-batching replicas.

    ``model`` is shared by every replica (one weight set in host/HBM
    memory; each replica owns private KV pools, prefix store and
    scheduler state). ``config`` applies to all replicas — the fleet
    is homogeneous, which is what makes failover's ledger replay
    placement-invariant. ``fault_injector`` (default: built from
    ``PT_FLAGS_fault_inject``) drives the ROUTER-level chaos sites
    ``replica_crash`` / ``replica_hang`` / ``probe_flaky``; engine-
    level sites keep firing inside each replica's own injector.
    """

    def __init__(self, model, config: Optional[EngineConfig] = None,
                 n_replicas: int = 2, *, drafter=None,
                 fault_injector: Optional[FaultInjector] = None,
                 seed: int = 0,
                 breaker_window: Optional[int] = None,
                 breaker_trip: Optional[int] = None,
                 breaker_cooldown: Optional[int] = None,
                 retry_schedule=None,
                 hang_ticks: int = 4):
        if n_replicas < 1:
            raise ValueError(
                f"n_replicas must be >= 1; got {n_replicas}")
        if hang_ticks < 1:
            raise ValueError(
                f"hang_ticks must be >= 1; got {hang_ticks}")
        cfg = config or EngineConfig()
        self.cfg = cfg
        self._hang_ticks = int(hang_ticks)
        window = int(breaker_window
                     if breaker_window is not None
                     else flags.flag("router_breaker_window"))
        trip = int(breaker_trip if breaker_trip is not None
                   else flags.flag("router_breaker_trip"))
        cooldown = int(breaker_cooldown
                       if breaker_cooldown is not None
                       else flags.flag("router_breaker_cooldown"))
        schedule = _parse_schedule(
            retry_schedule if retry_schedule is not None
            else flags.flag("router_retry_schedule"))
        for name, v in (("window", window), ("trip", trip),
                        ("cooldown", cooldown)):
            if int(v) < 1:
                # validate BEFORE any replica builds its device caches
                raise ValueError(f"breaker {name} must be >= 1; got {v}")
        self._replicas: List[_Replica] = []
        for i in range(n_replicas):
            eng = ContinuousBatchingEngine(model, cfg, drafter=drafter)
            br = CircuitBreaker(
                window, trip, cooldown, schedule,
                np.random.default_rng((0xB4EA, int(seed), i)))
            self._replicas.append(_Replica(i, eng, br))
        self._injector = (fault_injector if fault_injector is not None
                          else FaultInjector.from_flag())
        self._tick = 0
        # fleet-unique rid mint: next() on a C-level count iterator is
        # atomic under the GIL, so concurrent producer-thread
        # add_request calls can never mint the same rid (a plain
        # int += 1 read-modify-write could)
        self._rid_counter = itertools.count()
        # fleet-level admission queue: requests held while no replica
        # is routable (all saturated / draining / breaker-open) —
        # "one queue" in the sanitizer's rid-ownership invariant
        self._queue: collections.deque = collections.deque()
        # serializes placement (submit-to-replica + owner-map write)
        # across producer-thread add_request, the scheduler's
        # held-queue re-place, and failover's reclaim-and-re-place:
        # without it a producer preempted between submit and the owner
        # write could re-point a rid at a replica that just died (the
        # failover already moved it), or a fresh arrival could steal a
        # slot from an older held request mid-pop
        self._admit_lock = threading.Lock()
        # rid -> replica idx CURRENTLY responsible (live or finished
        # there); router-queued rids are absent by design
        self._owner: Dict[int, int] = {}
        # router-local terminal records (cancelled / expired while
        # held — they never reached an engine)
        self._finished: Dict[int, Request] = {}
        # SLO attainment for those router-local terminals (engine
        # timeouts/cancels account on their engine; a held request
        # that expires must not vanish from fleet goodput) — same
        # bucket shape as the engine's slo_stats, merged by
        # slo_snapshot()
        self.slo_stats: Dict[str, Dict[str, int]] = {}
        self._draining = False
        # host counters (available with telemetry off, like the
        # engine's prefix/spec/slo/resilience stats)
        self.fleet_stats = {
            "routed": 0, "affinity_routed": 0, "held": 0,
            "failovers": 0, "reclaimed": 0, "replayed": 0,
            "cancelled": 0, "timeouts": 0, "breaker_opens": 0,
        }
        self._tel = (observability.RouterTelemetry()
                     if observability.enabled() else None)
        self._tracer = None
        if self._tel is not None \
                and float(flags.flag("trace_sample")) > 0:
            self._tracer = observability.Tracer(
                engine_id=f"router{self._tel.router_id}")
        # fleet flight data (PT_FLAGS_timeseries): the router keeps
        # its own fixed-cadence windowed history over the FLEET
        # counters (routed/held/failovers/...) per fleet tick, beside
        # each replica engine's own store — same off == None no-op
        self._ts = None
        if bool(flags.flag("timeseries")):
            label = (f"router{self._tel.router_id}"
                     if self._tel is not None else None)
            self._ts = observability.TimeSeriesStore(label=label)
        self._san = None
        if bool(flags.flag("sanitize")):
            from ..analysis.sanitizer import EngineSanitizer

            self._san = EngineSanitizer(self)
        # process-wide fleet registry (weak): `dump --fleet` and the
        # merged-trace exports find this router without a handle
        observability.tracing.register_fleet(self)

    # ---------------- admission / routing ----------------
    def add_request(self, prompt, max_new_tokens: int = 32,
                    eos_token_id: Optional[int] = None,
                    **kwargs) -> int:
        """Validate (the engine's exact ``add_request`` checks, via
        the shared ``build_request``), assign a FLEET-unique rid, and
        place on the best replica — prefix affinity first, least
        loaded second — or hold at the router when none is routable.
        Accepts every ``ContinuousBatchingEngine.add_request`` keyword
        (sampling params, SLO class/targets, deadline, max_retries)."""
        req = build_request(
            next(self._rid_counter), prompt, max_new_tokens,
            eos_token_id, max_len=self.cfg.max_len, **kwargs)
        self._submit(req)
        return req.rid

    def _affinity_hashes(self, req: Request) -> List[bytes]:
        """Block-hash chain over the request's prefill ids, cached on
        the Request like the engine's own pool-block re-match cache —
        a router-held request is re-placed every fleet tick and must
        not re-hash each time (``_bump_retry`` already resets the
        cache when replay grows the ids)."""
        if req._hashes is None:
            ids = (np.concatenate([req.prompt,
                                   np.asarray(req.output, np.int64)])
                   if req.output else req.prompt)
            # the TENANT-aware chain (request_namespace): affinity must
            # hash exactly like the target engine's admission match,
            # or it would steer traffic at pages the replica can never
            # share across the namespace boundary
            req._hashes = block_hashes(
                ids, self.cfg.page_size,
                namespace=request_namespace(req))
        return req._hashes

    def _routable(self, rep: _Replica, bp: dict) -> bool:
        return (rep.breaker.state(self._tick) == BREAKER_CLOSED
                and self._tick >= rep.hung_until
                and not bp["draining"])

    def _pick(self, hashes: List[bytes]):
        """Best replica for this request, or ``(None, 0)`` when the
        fleet must hold it. Ranking (min): saturation first (a replica
        with room always beats one shedding), then PREFIX AFFINITY
        (tokens already resident — the block-hash chain routes shared-
        prefix traffic at its pages), then the degradation rung, then
        load (queue + active slots), then index for determinism. A
        failed replica needs no explicit exclusion: its breaker is
        open by the time failover re-submits, so ``_routable`` already
        filters it."""
        best = None
        best_key = None
        best_aff = 0
        for rep in self._replicas:
            bp = rep.engine.backpressure()
            if not self._routable(rep, bp):
                continue
            aff = rep.engine.prefix_affinity_tokens(hashes)
            load = bp["queue_depth"] \
                + bp["occupancy"] * rep.engine.cfg.max_slots
            key = (bool(bp["saturated"]), -aff,
                   bp["degradation_level"], load, rep.idx)
            if best_key is None or key < best_key:
                best, best_key, best_aff = rep, key, aff
        if best is not None and best_key[0]:
            # every routable replica is saturated: fleet-level shed —
            # hold at the router (composes with the replicas' own
            # shed_batch/throttle rungs instead of deepening their
            # queues), re-attempted each tick as finishers free slots
            return None, 0
        return best, best_aff

    def _place(self, req: Request) -> bool:
        """Route one request onto a replica; False when none is
        routable (caller holds it)."""
        hashes = self._affinity_hashes(req)
        rep, aff = self._pick(hashes)
        if rep is None:
            return False
        if req.output or req._retries:
            # a replay/handoff carries history: the target rebuilds it
            # from the token ledger (original instants preserved,
            # prompt+history re-prefilled) — the cross-engine move
            # contract
            rep.engine.admit_ledger(request_ledger(req))
        else:
            # first placement: this Request was built fleet-validated
            # with a fleet-unique rid — hand the object over directly,
            # no serialize/re-validate/duplicate-rid-scan round trip
            rep.engine.submit_request(req)
        self._owner[req.rid] = rep.idx
        self.fleet_stats["routed"] += 1
        if aff > 0:
            self.fleet_stats["affinity_routed"] += 1
        if self._tel is not None:
            self._tel.on_route(rep.idx, aff > 0)
        if self._tracer is not None:
            self._tracer.engine_event(
                "route", rid=int(req.rid), replica=rep.idx,
                affinity_tokens=int(aff),
                replayed_tokens=len(req.output))
        return True

    def _submit(self, req: Request) -> bool:
        # FIFO fairness: while OLDER requests sit held, a fresh
        # arrival must not steal capacity a finisher just freed —
        # it queues behind them and _place_queued places in order.
        # The lock covers _place_queued's pop window too: a held
        # request is OUTSIDE the queue while being placed, so the
        # emptiness check alone could let a fresh arrival jump it.
        with self._admit_lock:
            if not self._queue and self._place(req):
                return True
            self._queue.append(req)
            self._note_hold(req)
        return False

    def _note_hold(self, req: Request):
        self.fleet_stats["held"] += 1
        if self._tel is not None:
            self._tel.on_hold(len(self._queue))
        if self._tracer is not None:
            self._tracer.engine_event(
                "hold", rid=int(req.rid), queued=len(self._queue))

    def _place_queued(self):
        """FIFO re-attempt for router-held requests (head-of-line: a
        request that still can't place keeps everything behind it,
        preserving submission order like the engines' own queues).
        Pop-BEFORE-place, like the engine's own claim loop: placing
        first would leave a window where a producer-thread ``cancel``
        still finds the request in this queue and marks it terminal
        while a replica decodes it — the dual ownership the fleet
        sanitizer forbids. While popped, a racing cancel simply
        returns False for one call (the same transient the engine's
        admission claim window has)."""
        while True:
            with self._admit_lock:
                try:
                    req = self._queue.popleft()
                except IndexError:
                    break  # a racing cancel/expiry emptied the queue
                if not self._place(req):
                    self._queue.appendleft(req)
                    break

    def _slo_bucket(self, slo: str) -> Dict[str, int]:
        st = self.slo_stats.get(slo)
        if st is None:
            st = self.slo_stats[slo] = new_slo_bucket()
        return st

    def _expire_queue(self):
        """Deadline expiry for router-held requests — the fleet-level
        twin of the engines' per-tick ``_expire_deadlines``. An
        SLO-tracked request that expired while HELD is a real
        violation: it counts against fleet goodput exactly like an
        engine-side timeout would (the goodput-inflation dishonesty
        the engine's accounting exists to prevent).

        Runs under the admission lock: expiry moves a rid from the
        queue to the finish registry and bumps shared counters — the
        same mutation set producer-thread ``cancel`` makes under the
        lock; interleaving them could double-remove a request or
        lose stats updates."""
        now = time.perf_counter()
        with self._admit_lock:
            for req in list(self._queue):
                if req._deadline_t and now >= req._deadline_t:
                    self._queue.remove(req)
                    req.done = True
                    req.finish_reason = "timeout"
                    self._finished[req.rid] = req
                    self.fleet_stats["timeouts"] += 1
                    if req.slo is not None:
                        req.slo_met = False
                        st = self._slo_bucket(req.slo)
                        st["violated"] += 1
                        st["timeouts"] += 1
                    if self._tel is not None:
                        self._tel.on_held_timeout()
                    if self._tracer is not None:
                        self._tracer.engine_event(
                            "held_timeout", rid=int(req.rid),
                            queued=len(self._queue))

    # ---------------- fleet tick ----------------
    def step(self, max_chunk: int = 8) -> bool:
        """One FLEET tick: expire/place held requests, then tick every
        replica through its breaker + chaos seams. Returns False when
        no work remains anywhere."""
        san = self._san
        if san is not None:
            san.note_tick("router_step")
        self._tick += 1
        self._expire_queue()
        self._place_queued()
        for rep in self._replicas:
            self._tick_replica(rep, max_chunk)
        if self._tel is not None:
            # same routability verdict _pick and backpressure() use —
            # the gauge must not overreport while replicas drain
            routable = sum(
                1 for r in self._replicas
                if self._routable(r, r.engine.backpressure()))
            self._tel.on_fleet_state(routable, len(self._queue))
        if self._ts is not None:
            self._ts.on_tick(self._flight_collect)
        if san is not None:
            # under the admission lock: placement writes queue + owner
            # map as one atomic unit, so an unlocked read could catch a
            # producer thread mid-_place and report phantom dual
            # ownership
            with self._admit_lock:
                san.check_fleet(self, "router_step")
        return bool(self._queue) or any(
            self._has_work(r) for r in self._replicas)

    @staticmethod
    def _has_work(rep: _Replica) -> bool:
        return bool(rep.engine.active.any()) or bool(rep.engine._queue)

    def _recoverable(self, exc: BaseException) -> bool:
        """Router-level recovery policy: injected faults and XLA
        runtime errors that ESCAPED the engine's own recovery become
        whole-replica faults; host logic errors always propagate."""
        if isinstance(exc, InjectedFault):
            return True
        return bool(RUNTIME_ERRORS) and isinstance(exc, RUNTIME_ERRORS)

    def _tick_replica(self, rep: _Replica, max_chunk: int):
        br = rep.breaker
        was_open = br._state == BREAKER_OPEN
        st = br.advance(self._tick)
        if st == BREAKER_OPEN:
            return
        if was_open and st == BREAKER_HALF_OPEN:
            # the open→half_open commit is a reportable transition:
            # without it the breaker-state gauge jumps 1→0 and its
            # documented "2 half-open" encoding is unreachable, while
            # /healthz simultaneously reports "half_open"
            self._note_breaker(rep, opened=False)
            if self._tracer is not None:
                self._tracer.engine_event(
                    "breaker_half_open", replica=rep.idx,
                    tick=self._tick)
        inj = self._injector
        if inj is not None and inj.fire("replica_crash"):
            # whole-replica death: breaker opens immediately, the host
            # ledger is the ONLY survivor — reclaim + replay elsewhere,
            # rebuild the caches so a later canary returns it empty
            if br.trip_now(self._tick):
                self._note_breaker(rep, opened=True)
            self._reclaim(rep, hard=True, site="replica_crash")
            return
        if inj is not None and inj.fire("replica_hang"):
            rep.hung_until = self._tick + self._hang_ticks
        if self._tick < rep.hung_until:
            # stalled replica: a tick with pending work is a failed
            # health probe (no-progress); enough of them in the window
            # open the breaker and fail its work over
            if self._has_work(rep) and br.note_fault(self._tick):
                self._note_breaker(rep, opened=True)
                self._reclaim(rep, hard=False, site="replica_hang")
            return
        if inj is not None and inj.fire("probe_flaky"):
            # one flaky health-probe verdict: a FAULT in the window,
            # never an immediate failover — the breaker's trip
            # threshold is exactly the flap damping. The probe is
            # control-plane only: unless the breaker opens, the
            # replica keeps serving this tick (data plane unaffected)
            if br.note_fault(self._tick):
                self._note_breaker(rep, opened=True)
                self._reclaim(rep, hard=False, site="probe_flaky")
                return
        try:
            rep.engine.step_chunk(max_chunk)
        except BaseException as e:  # noqa: BLE001
            if not self._recoverable(e):
                raise
            if not isinstance(e, InjectedFault):
                # a REAL runtime error that escaped the engine's own
                # recovery (serve_recovery=off, or beyond its scope)
                # may have consumed donated device buffers — the
                # replica is untrusted NOW, not after `trip` more
                # faults: immediate open + reclaim + rebuild, the
                # engine's hard-recovery contract at fleet level
                if br.trip_now(self._tick):
                    self._note_breaker(rep, opened=True)
                self._reclaim(rep, hard=True, site=type(e).__name__)
                return
            # an escaped INJECTED fault fired pre-dispatch (caches
            # intact): a windowed replica fault, like a flaky probe
            if br.note_fault(self._tick):
                self._note_breaker(rep, opened=True)
                self._reclaim(rep, hard=False, site=type(e).__name__)
            return
        if st == BREAKER_HALF_OPEN:
            # canary passed: back in rotation
            br.note_ok(self._tick)
            self._note_breaker(rep, opened=False)
            if self._tracer is not None:
                self._tracer.engine_event(
                    "breaker_close", replica=rep.idx, tick=self._tick)

    def _note_breaker(self, rep: _Replica, opened: bool):
        if opened:
            self.fleet_stats["breaker_opens"] += 1
        if self._tel is not None:
            self._tel.on_breaker(rep.idx, rep.breaker._state, opened)
        if opened and self._tracer is not None:
            self._tracer.engine_event(
                "breaker_open", replica=rep.idx, tick=self._tick,
                reopen_at=rep.breaker.reopen_at)

    # ---------------- failover ----------------
    def _reclaim(self, rep: _Replica, hard: bool, site: str):
        """THE failover: pull every in-flight and queued request off a
        failed replica via the host token ledger and re-admit each on
        a survivor for deterministic replay. Expired requests time out
        (never replayed — their budget is spent), each survivor is
        charged one replay retry (the PR-7 bound: past it, reason
        ``"failed"``), and ``hard`` failures rebuild the replica's
        caches (untrusted device state; same shapes, zero new
        compiled programs).

        Runs under the admission lock (same wrapper idiom as the
        engine's sanitized ``step``/``_step_impl``): a producer-thread
        placement completes or waits — it can never interleave with
        the drain-and-re-place, so no request lands on the dead
        replica after the drain and no owner-map write goes stale."""
        with self._admit_lock:
            self._reclaim_impl(rep, hard, site)

    def _reclaim_impl(self, rep: _Replica, hard: bool, site: str):
        eng = rep.engine
        now = time.perf_counter()
        victims: List[Request] = []
        for slot in range(eng.cfg.max_slots):
            if eng.active[slot]:
                req = eng._slot_req[slot]
                eng._release_slot(slot)
                victims.append(req)
        while eng._queue:
            victims.append(eng._queue.popleft())
        if hard:
            eng.resilience_stats["rebuilds"] += 1
            eng._rebuild_caches()
        replayed = 0
        unplaced: List[Request] = []
        for req in victims:
            req.slot = None
            if req._deadline_t and now >= req._deadline_t:
                # a deadline that expired in flight must not buy a
                # fresh run on another replica — finish it here, with
                # the failed replica keeping the accounting
                eng.resilience_stats["timeouts"] += 1
                eng._finish_request(req, "timeout")
                continue
            if not eng._bump_retry(req):
                continue  # retries exhausted: finished "failed" here
            self._owner.pop(req.rid, None)
            if self._place(req):
                replayed += 1
                if self._tel is not None:
                    self._tel.on_replay()
            else:
                unplaced.append(req)
        if unplaced:
            # victims are the OLDEST traffic: they hold at the queue
            # FRONT, ahead of younger arrivals (the engine's own
            # quarantine-requeue order), original order preserved
            self._queue.extendleft(reversed(unplaced))
            for req in unplaced:
                self._note_hold(req)
        if not victims:
            # a re-open with nothing to move (e.g. a flaky canary on
            # a replica its original failover already emptied) is a
            # breaker event, not a failover — counting it would let a
            # vacuous re-open satisfy "failovers >= 1" determinism
            # checks without a single request ever moving
            return
        rep.failovers += 1
        self.fleet_stats["failovers"] += 1
        self.fleet_stats["reclaimed"] += len(victims)
        self.fleet_stats["replayed"] += replayed
        if self._tel is not None:
            self._tel.on_failover(rep.idx, len(victims))
        if self._tracer is not None:
            self._tracer.engine_event(
                "failover", replica=rep.idx, site=site, hard=hard,
                reclaimed=len(victims), replayed=replayed)

    # ---------------- request lifecycle ----------------
    def cancel(self, rid: int) -> bool:
        """Cancel anywhere in the fleet: router-held requests leave
        the hold queue; placed ones cancel on their owner replica
        (slot/pages/prefix refs released there). A cancelled rid can
        never be replayed by a later failover — it is in a terminal
        registry, not a queue or slot.

        Thread contract: the router-held path is producer-safe — it
        runs under the admission lock, so it cannot interleave with
        placement, expiry, or a sanitized step()'s fleet snapshot
        (which holds the same lock), and concurrent cancels cannot
        lose ``fleet_stats`` updates; the PLACED path delegates to
        ``engine.cancel``, which frees slots and pages and so must
        run on the scheduler thread — same contract as the engine
        documents."""
        with self._admit_lock:
            req = next((r for r in self._queue if r.rid == rid), None)
            if req is not None:
                self._queue.remove(req)
                req.done = True
                req.cancelled = True
                req.finish_reason = "cancel"
                self._finished[rid] = req
                self.fleet_stats["cancelled"] += 1
                if req.slo is not None:
                    # cancelled, never a violation — same split the
                    # engine's accounting makes
                    self._slo_bucket(req.slo)["cancelled"] += 1
                if self._tel is not None:
                    self._tel.on_held_cancel()
                if self._tracer is not None:
                    self._tracer.engine_event(
                        "held_cancel", rid=int(req.rid),
                        queued=len(self._queue))
                return True
        ridx = self._owner.get(rid)
        if ridx is None:
            return False
        return self._replicas[ridx].engine.cancel(rid)

    def result(self, rid: int) -> Optional[Request]:
        """The finished :class:`Request` for ``rid`` (None while in
        flight): router-local terminals first, then the owner
        replica's finish registry."""
        req = self._finished.get(rid)
        if req is not None:
            return req
        ridx = self._owner.get(rid)
        if ridx is None:
            return None
        return self._replicas[ridx].engine._finished.get(rid)

    def run(self, prompts: Sequence, max_new_tokens: int = 32,
            eos_token_id: Optional[int] = None,
            max_chunk: int = 8) -> List[Request]:
        """Submit all prompts and drive the fleet to completion;
        returns finished Requests in submission order."""
        rids = [self.add_request(p, max_new_tokens, eos_token_id)
                for p in prompts]
        while self.step(max_chunk):
            pass
        out = []
        for rid in rids:
            req = self.result(rid)
            if req is not None:
                out.append(req)
        return out

    # ---------------- drain / resume ----------------
    def drain(self, deadline_ms: Optional[float] = None,
              max_chunk: int = 8) -> dict:
        """Fleet drain: every replica drains (sharing one absolute
        deadline), and the aggregate ``"unfinished"`` handoff payload
        carries each replica's leftover ledgers PLUS the router-held
        requests — everything a successor fleet would need to
        ``admit_ledger`` and continue bit-identically."""
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0; got {deadline_ms}")
        self._draining = True
        t_end = (None if deadline_ms is None
                 else time.perf_counter() + deadline_ms / 1e3)
        unfinished: List[dict] = []
        per_replica = []
        expired = 0
        for rep in self._replicas:
            remaining = None
            if t_end is not None:
                remaining = max((t_end - time.perf_counter()) * 1e3,
                                1.0)
            s = rep.engine.drain(deadline_ms=remaining,
                                 max_chunk=max_chunk)
            expired += s["expired"]
            unfinished.extend(s["unfinished"])
            per_replica.append({"replica": rep.idx,
                                "expired": s["expired"],
                                "queued": s["queued"]})
        unfinished.extend(request_ledger(r) for r in list(self._queue))
        if self._tracer is not None:
            self._tracer.engine_event(
                "fleet_drain", expired=expired,
                unfinished=len(unfinished))
        return {"drained": True, "expired": expired,
                "queued": len(self._queue),
                "replicas": per_replica,
                "unfinished": unfinished}

    def resume(self):
        self._draining = False
        for rep in self._replicas:
            rep.engine.resume()

    # ---------------- scrape readers (copy-on-read) ----------------
    def backpressure(self) -> dict:
        """Fleet-aggregate admission readiness, shaped like the
        engine's: ``saturated`` only when NO replica can take traffic
        (the healthz 503 condition for the front door), the WORST
        degradation rung, plus a per-replica breakdown a dashboard or
        an outer load balancer can steer on."""
        if self._san is not None:
            self._san.check_read("backpressure")
        reps = []
        total_q = len(self._queue)
        free = 0
        routable = 0
        unsaturated = 0
        active = 0.0
        slots = 0.0
        level = 0
        degraded = False
        for rep in list(self._replicas):
            bp = rep.engine.backpressure()
            rt = self._routable(rep, bp)
            if rt:
                routable += 1
                free += bp["free_slots"]
                if not bp["saturated"]:
                    unsaturated += 1
            total_q += bp["queue_depth"]
            n = rep.engine.cfg.max_slots
            active += bp["occupancy"] * n
            slots += n
            level = max(level, bp["degradation_level"])
            degraded = degraded or bp["degraded"]
            reps.append({
                "replica": rep.idx,
                "breaker": BREAKER_NAMES[
                    rep.breaker.state(self._tick)],
                "routable": rt,
                "saturated": bp["saturated"],
                "queue_depth": bp["queue_depth"],
                "free_slots": bp["free_slots"],
                "degradation_level": bp["degradation_level"],
                "draining": bp["draining"],
            })
        return {
            "queue_depth": total_q,
            "free_slots": free,
            "occupancy": active / slots if slots else 0.0,
            "saturated": unsaturated == 0,
            "draining": self._draining,
            "degraded": degraded,
            "degradation_level": level,
            "routable_replicas": routable,
            "replicas": reps,
        }

    def _flight_collect(self) -> dict:
        """Fleet counters + gauges for one router time-series window
        (scheduler-thread only; the replicas keep their own engine-
        labeled stores)."""
        counters = {k: float(v)
                    for k, v in list(self.fleet_stats.items())}
        routable = sum(
            1 for rep in self._replicas
            if self._routable(rep, rep.engine.backpressure()))
        gauges = {
            "queue_depth": float(len(self._queue)),
            "routable_replicas": float(routable),
            "n_replicas": float(len(self._replicas)),
        }
        return {"counters": counters, "gauges": gauges,
                "percentiles": {}}

    def timeline_snapshot(self) -> dict:
        """The FLEET time-series view: the router's own windowed
        fleet-counter history plus every replica engine's timeline
        (``{"enabled": False}`` when PT_FLAGS_timeseries is off).
        Copy-on-read — served at ``/timeline`` on the fleet metrics
        server."""
        if self._san is not None:
            self._san.check_read("timeline_snapshot")
        if self._ts is None:
            return {"enabled": False}
        st = self._ts.snapshot()
        return {"enabled": True, "router": st,
                "replicas": [rep.engine.timeline_snapshot()
                             for rep in list(self._replicas)]}

    def fleet_snapshot(self) -> dict:
        """Host-side router counters + breaker states (available with
        telemetry off, like every engine snapshot). ``alerts``
        aggregates every replica's alert-rule state — the fleet view
        of "which replica is burning its SLO budget"."""
        if self._san is not None:
            self._san.check_read("fleet_snapshot")
        st = {k: v for k, v in list(self.fleet_stats.items())}
        st["tick"] = self._tick
        st["n_replicas"] = len(self._replicas)
        st["queue_depth"] = len(self._queue)
        st["draining"] = self._draining
        st["breakers"] = [
            dict(rep.breaker.snapshot(self._tick), replica=rep.idx,
                 failovers=rep.failovers)
            for rep in list(self._replicas)]
        st["injector"] = (self._injector.snapshot()
                          if self._injector is not None
                          else {"enabled": False})
        alerts = {"enabled": False, "fired": 0, "active": []}
        for rep in list(self._replicas):
            asn = rep.engine.alerts_snapshot()
            if not asn.get("enabled"):
                continue
            alerts["enabled"] = True
            alerts["fired"] += asn["fired_total"]
            for rule in list(asn["active"]):
                alerts["active"].append(
                    {"replica": rep.idx, "rule": rule})
        st["alerts"] = alerts
        return st

    def slo_snapshot(self) -> dict:
        """FLEET-level SLO attainment: every replica's per-class
        counters merged with the router's own terminal records (held
        requests that expired or were cancelled before placement) —
        the goodput a single replica's snapshot cannot see. Same
        shape as ``engine.slo_snapshot()``."""
        if self._san is not None:
            self._san.check_read("slo_snapshot")
        classes: Dict[str, Dict[str, float]] = {}

        def merge(cls, st):
            agg = classes.setdefault(cls, {})
            for k, v in list(st.items()):
                if k == "goodput" or not isinstance(v, (int, float)):
                    continue
                agg[k] = agg.get(k, 0) + v

        for rep in list(self._replicas):
            for cls, st in list(
                    rep.engine.slo_snapshot()["classes"].items()):
                merge(cls, st)
        for cls, st in list(self.slo_stats.items()):
            merge(cls, st)
        met = violated = 0
        for st in classes.values():
            tracked = st.get("met", 0) + st.get("violated", 0)
            st["goodput"] = st["met"] / tracked if tracked else None
            met += st.get("met", 0)
            violated += st.get("violated", 0)
        tracked = met + violated
        return {"classes": classes, "met": met, "violated": violated,
                "goodput": met / tracked if tracked else None}

    def tenant_snapshot(self) -> dict:
        """FLEET-level per-tenant accounting: every replica's
        ``tenant_snapshot`` merged key-by-key (counts sum; the
        scheduler sub-doc reports each replica's policy). Same
        copy-on-read contract as the engine reader."""
        if self._san is not None:
            self._san.check_read("tenant_snapshot")
        tenants: Dict[str, Dict[str, float]] = {}
        preemptions = 0
        policies = []
        for rep in list(self._replicas):
            snap = rep.engine.tenant_snapshot()
            sched = snap.get("scheduler") or {}
            policies.append(sched.get("policy"))
            preemptions += int(sched.get("preemptions", 0) or 0)
            for key, st in list(snap["tenants"].items()):
                agg = tenants.setdefault(key, {})
                for k, v in list(st.items()):
                    if isinstance(v, (int, float)):
                        agg[k] = agg.get(k, 0) + v
        # router-held requests count as queued against their tenant
        for req in list(self._queue):
            agg = tenants.setdefault(req.tenant or "-", {})
            agg["queued"] = agg.get("queued", 0) + 1
        return {
            "tenants": tenants,
            "scheduler": {
                "policy": (policies[0] if policies
                           and all(p == policies[0]
                                   for p in policies)
                           else policies),
                "preemptions": preemptions,
            },
        }

    def fleet_chrome_trace(self) -> dict:
        """ONE merged Perfetto-loadable trace for the whole fleet:
        the router's route/failover/breaker event stream plus every
        replica's request+step tracks, with a failed-over rid's spans
        on BOTH replicas joined by flow events
        (``observability.tracing.fleet_chrome_trace``). Served at
        ``/trace?fleet=1`` on the fleet metrics server."""
        return observability.tracing.fleet_chrome_trace(self)

    def metrics_snapshot(self) -> dict:
        """ONE fleet document: router registry aggregates (when
        telemetry is on), the host-side fleet snapshot, the merged
        fleet SLO view, and every replica's own unified
        ``metrics_snapshot`` — what the aggregate ``/healthz``
        embeds."""
        if self._san is not None:
            self._san.check_read("metrics_snapshot")
        snap = ({"telemetry": "off"} if self._tel is None
                else self._tel.snapshot())
        snap["fleet"] = self.fleet_snapshot()
        snap["slo"] = self.slo_snapshot()
        snap["tenants"] = self.tenant_snapshot()
        snap["replicas"] = [rep.engine.metrics_snapshot()
                            for rep in list(self._replicas)]
        return snap
