"""Inference / deployment stack.

Parity: paddle_infer (paddle/fluid/inference/api/ — ``Config`` /
``create_predictor`` / ``Predictor.run``): the reference loads a static
program, runs ~100 ir fusion passes + memory-optimize, optionally carves
TensorRT subgraphs, then executes on a per-predictor stream.

TPU-native: all of that is one ``jax.jit(...).lower().compile()`` — XLA
is the fusion pipeline, memory planner and engine cache. The Predictor
AOT-compiles two programs per (batch, seq-bucket): *prefill* (prompt →
logits + primed KV cache; the TTFT path) and *decode-step* (one token,
donated KV cache, in-place update). Sequence-length bucketing replaces
TRT dynamic-shape profiles.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.functional import extract_params, functional_call
from ..core.module import Layer


class Config:
    """Parity: paddle_infer.Config. Device/IR knobs that XLA subsumes are
    accepted and recorded (introspectable via ``summary()``), not errors."""

    def __init__(self, model_dir: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.model_dir = model_dir
        self.params_file = params_file
        self.max_batch_size = 1
        self.max_seq_len = 2048
        self.decode_dtype = jnp.bfloat16
        self.seq_buckets: Sequence[int] = (128, 512, 1024, 2048)
        self._memory_optim = True
        self._ir_optim = True
        self._records: Dict[str, object] = {}

    # ---- parity knobs (recorded; XLA handles the substance) ----
    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def enable_use_gpu(self, *a, **k):
        self._records["enable_use_gpu"] = (a, k)

    def set_cpu_math_library_num_threads(self, n):
        self._records["cpu_threads"] = n

    def summary(self):
        return {
            "model_dir": self.model_dir,
            "max_batch_size": self.max_batch_size,
            "max_seq_len": self.max_seq_len,
            "seq_buckets": list(self.seq_buckets),
            **self._records,
        }


class Predictor:
    """Causal-LM predictor with AOT prefill/decode programs."""

    def __init__(self, model: Layer, config: Optional[Config] = None):
        self.model = model
        self.config = config or Config()
        self.params = extract_params(model)
        model.eval()
        self._prefill_cache = {}
        self._decode_fn = None
        self._ttft_ms: Optional[float] = None

    # ------------------------------------------------------------------
    def _bucket(self, seq_len: int) -> int:
        for b in self.config.seq_buckets:
            if seq_len <= b:
                return b
        return self.config.max_seq_len

    def _get_prefill(self, batch: int, bucket: int):
        key = (batch, bucket)
        if key not in self._prefill_cache:
            max_len = self.config.max_seq_len

            def prefill(params, ids, caches):
                pos = jnp.broadcast_to(
                    jnp.arange(ids.shape[1])[None, :], ids.shape
                )
                logits, caches = functional_call(
                    self.model, params, ids, position_ids=pos,
                    kv_caches=caches, cache_index=0,
                )
                return logits, caches

            caches = self.model.init_kv_caches(
                batch, max_len, dtype=self.config.decode_dtype
            )
            ids_shape = jax.ShapeDtypeStruct((batch, bucket), jnp.int32)
            lowered = jax.jit(prefill).lower(self.params, ids_shape, caches)
            self._prefill_cache[key] = (lowered.compile(), caches)
        return self._prefill_cache[key]

    def _get_decode(self, batch: int):
        if self._decode_fn is None:
            max_len = self.config.max_seq_len

            def decode_step(params, tok, caches, idx):
                pos = jnp.full((batch, 1), idx, jnp.int32)
                logits, caches = functional_call(
                    self.model, params, tok, position_ids=pos,
                    kv_caches=caches, cache_index=idx,
                )
                return jnp.argmax(logits[:, -1, :], axis=-1), caches

            self._decode_fn = jax.jit(decode_step, donate_argnums=(2,))
        return self._decode_fn

    # ------------------------------------------------------------------
    def run(self, input_ids) -> jax.Array:
        """One-shot forward (parity: Predictor::Run) → logits."""
        ids = jnp.asarray(input_ids)
        return functional_call(self.model, self.params, ids)

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
    ) -> np.ndarray:
        """Greedy decode with primed KV cache; records TTFT."""
        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        batch, prompt_len = ids.shape
        bucket = self._bucket(prompt_len)
        pad = bucket - prompt_len
        padded = np.pad(ids, ((0, 0), (0, pad)))

        t0 = time.perf_counter()
        prefill, cache_proto = self._get_prefill(batch, bucket)
        logits, caches = prefill(
            self.params, jnp.asarray(padded, jnp.int32), cache_proto
        )
        # next token comes from the last *real* prompt position
        next_tok = jnp.argmax(logits[:, prompt_len - 1, :], axis=-1)
        next_tok.block_until_ready()
        self._ttft_ms = (time.perf_counter() - t0) * 1e3

        decode = self._get_decode(batch)
        out: List[np.ndarray] = [np.asarray(next_tok)]
        tok = next_tok[:, None].astype(jnp.int32)
        for i in range(max_new_tokens - 1):
            idx = prompt_len + i
            nxt, caches = decode(self.params, tok, caches, idx)
            out.append(np.asarray(nxt))
            if eos_token_id is not None and bool(
                np.all(out[-1] == eos_token_id)
            ):
                break
            tok = nxt[:, None].astype(jnp.int32)
        return np.stack(out, axis=1)

    @property
    def last_ttft_ms(self):
        return self._ttft_ms


from .paged import (  # noqa: E402,F401
    PagedLayerCache,
    PagedState,
    PagePool,
    init_paged_pool,
    paged_attention,
)
from .serving import (  # noqa: E402,F401
    ContinuousBatchingEngine,
    EngineConfig,
    Request,
)


def create_predictor(model_or_config, config: Optional[Config] = None):
    """Parity: paddle_infer.create_predictor. Accepts a Layer directly
    (the TPU-native path) or a Config whose model_dir holds a saved
    state_dict + a model factory is the caller's job."""
    if isinstance(model_or_config, Layer):
        return Predictor(model_or_config, config)
    raise TypeError(
        "pass a Layer (TPU-native path); program-file loading arrives with "
        "the serialization format"
    )
