"""Inference / deployment stack.

Parity: paddle_infer (paddle/fluid/inference/api/ — ``Config`` /
``create_predictor`` / ``Predictor.run``): the reference loads a static
program, runs ~100 ir fusion passes + memory-optimize, optionally carves
TensorRT subgraphs, then executes on a per-predictor stream.

TPU-native: all of that is one ``jax.jit(...).lower().compile()`` — XLA
is the fusion pipeline, memory planner and engine cache. The Predictor
AOT-compiles two programs per (batch, seq-bucket): *prefill* (prompt →
logits + primed KV cache; the TTFT path) and *decode-step* (one token,
donated KV cache, in-place update). Sequence-length bucketing replaces
TRT dynamic-shape profiles.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.functional import extract_params, functional_call
from ..core.module import Layer


class Config:
    """Parity: paddle_infer.Config. Device/IR knobs that XLA subsumes are
    accepted and recorded (introspectable via ``summary()``), not errors."""

    def __init__(self, model_dir: Optional[str] = None,
                 params_file: Optional[str] = None):
        self.model_dir = model_dir
        self.params_file = params_file
        self.max_batch_size = 1
        self.max_seq_len = 2048
        self.decode_dtype = jnp.bfloat16
        self.seq_buckets: Sequence[int] = (128, 512, 1024, 2048)
        self._memory_optim = True
        self._ir_optim = True
        self._records: Dict[str, object] = {}

    # ---- parity knobs (recorded; XLA handles the substance) ----
    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = flag

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = flag

    def enable_use_gpu(self, *a, **k):
        self._records["enable_use_gpu"] = (a, k)

    def set_cpu_math_library_num_threads(self, n):
        self._records["cpu_threads"] = n

    def summary(self):
        return {
            "model_dir": self.model_dir,
            "max_batch_size": self.max_batch_size,
            "max_seq_len": self.max_seq_len,
            "seq_buckets": list(self.seq_buckets),
            **self._records,
        }


class Predictor:
    """Causal-LM predictor with AOT prefill/decode programs."""

    def __init__(self, model: Layer, config: Optional[Config] = None):
        self.model = model
        self.config = config or Config()
        self.params = extract_params(model)
        model.eval()
        self._prefill_cache = {}
        self._decode_fns: Dict[int, object] = {}
        self._pick_fns: Dict[tuple, object] = {}
        self._ttft_ms: Optional[float] = None

    # ------------------------------------------------------------------
    def _bucket(self, seq_len: int) -> int:
        for b in self.config.seq_buckets:
            if seq_len <= b:
                return b
        return self.config.max_seq_len

    def _get_prefill(self, batch: int, bucket: int):
        key = (batch, bucket)
        if key not in self._prefill_cache:
            max_len = self.config.max_seq_len

            def prefill(params, ids, caches):
                pos = jnp.broadcast_to(
                    jnp.arange(ids.shape[1])[None, :], ids.shape
                )
                logits, caches = functional_call(
                    self.model, params, ids, position_ids=pos,
                    kv_caches=caches, cache_index=0,
                )
                return logits, caches

            caches = self.model.init_kv_caches(
                batch, max_len, dtype=self.config.decode_dtype
            )
            ids_shape = jax.ShapeDtypeStruct((batch, bucket), jnp.int32)
            lowered = jax.jit(prefill).lower(self.params, ids_shape, caches)
            self._prefill_cache[key] = (lowered.compile(), caches)
        return self._prefill_cache[key]

    def _get_decode(self, batch: int):
        # keyed by batch: the closure bakes the position shape in, and
        # beam search calls with batch·num_beams rows
        if batch not in self._decode_fns:

            def decode_step(params, tok, caches, idx):
                pos = jnp.full((batch, 1), idx, jnp.int32)
                logits, caches = functional_call(
                    self.model, params, tok, position_ids=pos,
                    kv_caches=caches, cache_index=idx,
                )
                return logits[:, -1, :], caches

            self._decode_fns[batch] = jax.jit(
                decode_step, donate_argnums=(2,))
        return self._decode_fns[batch]

    def _get_pick(self, batch, buf_len, sampling, top_k, top_p,
                  temperature, repetition_penalty):
        """Compiled per-token processor stack, cached per config so a
        second generate() call never re-traces (the compile would
        otherwise land inside the TTFT measurement every call). ``slot``
        is a traced scalar: one program serves every step."""
        from .. import generation as G

        key = (batch, buf_len, sampling, top_k, top_p, temperature,
               repetition_penalty)
        if key not in self._pick_fns:

            @jax.jit
            def pick(logit_row, rng, slot, gen_buf, gen_mask):
                if sampling:
                    rng, sub = jax.random.split(rng)
                    tok = G.sample_token(
                        logit_row, sub, temperature=temperature,
                        top_k=top_k, top_p=top_p, generated_ids=gen_buf,
                        repetition_penalty=repetition_penalty,
                        generated_mask=gen_mask)
                else:
                    proc = G.process_logits(
                        logit_row, generated_ids=gen_buf,
                        repetition_penalty=repetition_penalty,
                        generated_mask=gen_mask)
                    tok = jnp.argmax(proc, axis=-1)
                gen_buf = jax.lax.dynamic_update_slice_in_dim(
                    gen_buf, tok[:, None].astype(jnp.int32), slot, axis=1)
                gen_mask = jax.lax.dynamic_update_slice_in_dim(
                    gen_mask, jnp.ones((batch, 1), bool), slot, axis=1)
                return tok, rng, gen_buf, gen_mask

            self._pick_fns[key] = pick
        return self._pick_fns[key]

    def _get_beam_logprobs(self, batch, num_beams, max_new_tokens,
                           prompt_len, temperature, repetition_penalty):
        """Compiled beam logits-processor + log-softmax (the reference's
        beam path applies repetition penalty over prompt+beam tokens and
        temperature; top-k/top-p are sampling-only). Cached per config —
        one program serves every step (t is traced)."""
        from .. import generation as G

        key = ("beam", batch, num_beams, max_new_tokens, prompt_len,
               temperature, repetition_penalty)
        if key not in self._pick_fns:
            step_pos = jnp.arange(max_new_tokens)

            @jax.jit
            def lp_fn(logits, beam_tokens, t, prompt_flat):
                if repetition_penalty != 1.0 or temperature != 1.0:
                    toks_flat = beam_tokens.reshape(
                        batch * num_beams, max_new_tokens)
                    buf = jnp.concatenate([prompt_flat, toks_flat],
                                          axis=1)
                    mask = jnp.concatenate([
                        jnp.ones(prompt_flat.shape, bool),
                        jnp.broadcast_to(step_pos[None] < t,
                                         toks_flat.shape),
                    ], axis=1)
                    logits = G.process_logits(
                        logits, temperature=temperature,
                        generated_ids=buf,
                        repetition_penalty=repetition_penalty,
                        generated_mask=mask)
                return jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=-1)

            self._pick_fns[key] = lp_fn
        return self._pick_fns[key]

    # ------------------------------------------------------------------
    def run(self, input_ids) -> jax.Array:
        """One-shot forward (parity: Predictor::Run) → logits."""
        ids = jnp.asarray(input_ids)
        return functional_call(self.model, self.params, ids)

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        decode_strategy: str = "greedy_search",
        top_k: int = 0,
        top_p: float = 1.0,
        temperature: float = 1.0,
        repetition_penalty: float = 1.0,
        num_beams: int = 1,
        length_penalty: float = 0.0,
        seed: int = 0,
    ) -> np.ndarray:
        """Parity: PaddleNLP GenerationMixin.generate — greedy_search /
        sampling (top-k, top-p, temperature, repetition penalty) /
        beam_search (KV cache reordered per step via one batched gather).
        Records TTFT on the prefill."""
        if decode_strategy == "beam_search" or num_beams > 1:
            return self._beam_generate(
                input_ids, max_new_tokens, max(num_beams, 2),
                eos_token_id, length_penalty, temperature,
                repetition_penalty)
        from .. import generation as G

        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        batch, prompt_len = ids.shape
        bucket = self._bucket(prompt_len)
        pad = bucket - prompt_len
        padded = np.pad(ids, ((0, 0), (0, pad)))
        sampling = decode_strategy == "sampling"
        rng = jax.random.PRNGKey(seed)

        t0 = time.perf_counter()
        prefill, cache_proto = self._get_prefill(batch, bucket)
        logits, caches = prefill(
            self.params, jnp.asarray(padded, jnp.int32), cache_proto
        )
        # next token comes from the last *real* prompt position
        last = logits[:, prompt_len - 1, :]
        # seen-token buffer for the repetition penalty: the PROMPT counts
        # too (PaddleNLP penalizes full input_ids), then each generated
        # token is appended
        buf_len = prompt_len + max_new_tokens
        gen_buf = jnp.zeros((batch, buf_len), jnp.int32)
        gen_buf = gen_buf.at[:, :prompt_len].set(jnp.asarray(ids, jnp.int32))
        gen_mask = jnp.zeros((batch, buf_len), bool)
        gen_mask = gen_mask.at[:, :prompt_len].set(True)

        pick = self._get_pick(batch, buf_len, sampling, top_k, top_p,
                              temperature, repetition_penalty)

        next_tok, rng, gen_buf, gen_mask = pick(
            last, rng, jnp.int32(prompt_len), gen_buf, gen_mask)
        next_tok.block_until_ready()
        self._ttft_ms = (time.perf_counter() - t0) * 1e3

        decode = self._get_decode(batch)
        out: List[np.ndarray] = [np.asarray(next_tok)]
        tok = next_tok[:, None].astype(jnp.int32)
        for i in range(max_new_tokens - 1):
            idx = prompt_len + i
            logit_row, caches = decode(self.params, tok, caches, idx)
            nxt, rng, gen_buf, gen_mask = pick(
                logit_row, rng, jnp.int32(prompt_len + i + 1),
                gen_buf, gen_mask)
            out.append(np.asarray(nxt))
            if eos_token_id is not None and bool(
                np.all(out[-1] == eos_token_id)
            ):
                break
            tok = nxt[:, None].astype(jnp.int32)
        return np.stack(out, axis=1)

    def _beam_generate(self, input_ids, max_new_tokens, num_beams,
                       eos_token_id, length_penalty, temperature=1.0,
                       repetition_penalty=1.0):
        from .. import generation as G

        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        batch, prompt_len = ids.shape
        bucket = self._bucket(prompt_len)
        # expand each row to num_beams contiguous copies (batch-major)
        tiled = np.repeat(ids, num_beams, axis=0)
        padded = np.pad(tiled, ((0, 0), (0, bucket - prompt_len)))
        prompt_flat = jnp.asarray(tiled, jnp.int32)
        lp_fn = self._get_beam_logprobs(
            batch, num_beams, max_new_tokens, prompt_len, temperature,
            repetition_penalty)

        def beam_logprobs(logits, state, t):
            return lp_fn(logits, state.tokens, jnp.int32(t), prompt_flat)

        t0 = time.perf_counter()
        prefill, cache_proto = self._get_prefill(batch * num_beams, bucket)
        logits, caches = prefill(
            self.params, jnp.asarray(padded, jnp.int32), cache_proto
        )
        state = G.BeamState(batch, num_beams, max_new_tokens)
        lp = beam_logprobs(logits[:, prompt_len - 1, :], state, 0)
        state, beam_idx, next_tok = G.beam_step(
            state, lp, 0, eos_token_id)
        caches = G.reorder_cache(caches, beam_idx)
        next_tok.block_until_ready()
        self._ttft_ms = (time.perf_counter() - t0) * 1e3

        decode = self._get_decode(batch * num_beams)
        tok = next_tok.reshape(-1, 1).astype(jnp.int32)
        for i in range(max_new_tokens - 1):
            logit_row, caches = decode(
                self.params, tok, caches, prompt_len + i)
            lp = beam_logprobs(logit_row, state, i + 1)
            state, beam_idx, next_tok = G.beam_step(
                state, lp, i + 1, eos_token_id)
            caches = G.reorder_cache(caches, beam_idx)
            tok = next_tok.reshape(-1, 1).astype(jnp.int32)
            if eos_token_id is not None and bool(
                jnp.all(state.finished)
            ):
                break
        tokens, scores = G.beam_finalize(state, length_penalty)
        self._last_beam_scores = np.asarray(scores)
        return np.asarray(tokens)

    @property
    def last_ttft_ms(self):
        return self._ttft_ms


from .paged import (  # noqa: E402,F401
    PagedLayerCache,
    PagedState,
    PagePool,
    append_kv_chunk,
    init_paged_pool,
    paged_attention,
)
from .prefix_cache import (  # noqa: E402,F401
    ContigPrefixStore,
    PagedPrefixStore,
    block_hashes,
)
from .resilience import (  # noqa: E402,F401
    DegradationController,
    FaultInjector,
    InjectedFault,
)
from .router import (  # noqa: E402,F401
    CircuitBreaker,
    EngineRouter,
)
from .serving import (  # noqa: E402,F401
    ContinuousBatchingEngine,
    EngineConfig,
    MetricsServer,
    Request,
    build_request,
    request_ledger,
    start_metrics_server,
)
from .spec_decode import (  # noqa: E402,F401
    Drafter,
    NgramDrafter,
)


def create_predictor(model_or_config, config: Optional[Config] = None):
    """Parity: paddle_infer.create_predictor. Accepts a Layer directly
    (the TPU-native path) or a Config whose model_dir holds a saved
    state_dict + a model factory is the caller's job."""
    if isinstance(model_or_config, Layer):
        return Predictor(model_or_config, config)
    raise TypeError(
        "pass a Layer (TPU-native path); program-file loading arrives with "
        "the serialization format"
    )
