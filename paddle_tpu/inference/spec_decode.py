"""Speculative-decoding drafters for the continuous-batching engine.

Decode is pinned to the weight-bandwidth roofline: every accepted token
costs one full forward pass that streams all model weights from HBM.
Speculative decoding amortizes that stream — a cheap DRAFTER proposes up
to K candidate tokens per slot, the target model scores all of them in
ONE fixed ``[slots, K+1]`` pass (the engine's verify program — the same
program shape as PR 4's chunked prefill), and greedy acceptance keeps
the longest prefix of drafts that match the target's own argmax chain.
Greedy outputs are therefore IDENTICAL to plain decode in every case;
the only thing at stake is how many tokens each weight stream buys.

The built-in drafter is N-GRAM PROMPT LOOKUP (self-drafting): it matches
the slot's most recent token suffix against the slot's OWN
prompt+generation history and proposes the continuation of the most
recent earlier occurrence. No draft-model weights, no device work —
pure host-side numpy, so the whole path runs (and is tested) on CPU.
Repetitive traffic — code, JSON, templated answers, extractive QA — is
exactly where the suffix recurs and acceptance is high.

``Drafter`` is the protocol seam: anything with a
``propose(history, k) -> np.ndarray`` method plugs into
``ContinuousBatchingEngine(..., drafter=...)``. A small draft MODEL
would implement the same method (batching its own forward over the
histories host-side or in its own compiled program); the engine only
ever sees proposed token ids.
"""

from __future__ import annotations

import numpy as np

_EMPTY = np.zeros((0,), np.int64)


class Drafter:
    """Protocol seam for speculative-decoding drafters.

    ``propose(history, k)`` receives one slot's full token history
    (prompt + generated, the last entry being the token the next decode
    step will consume) and returns up to ``k`` proposed NEXT tokens as
    a 1-D int array (empty = no proposal; the slot falls back to normal
    one-token decode). Must be pure host-side and cheap relative to a
    decode step — it runs per slot per scheduler tick.
    """

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        raise NotImplementedError


class NgramDrafter(Drafter):
    """Prompt-lookup n-gram drafter (self-drafting, no draft model).

    Tries suffix lengths ``max_ngram`` down to ``min_ngram``: for the
    first length whose suffix has an earlier occurrence in the history,
    proposes the tokens FOLLOWING the most recent such occurrence
    (recency wins — local repetition beats a stale prompt match).
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram; got "
                f"min={min_ngram} max={max_ngram}")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        h = np.ascontiguousarray(
            np.asarray(history).reshape(-1), np.int64)
        if k <= 0 or h.size < self.min_ngram + 1:
            return _EMPTY
        for n in range(min(self.max_ngram, h.size - 1),
                       self.min_ngram - 1, -1):
            pat = h[h.size - n:]
            windows = np.lib.stride_tricks.sliding_window_view(h, n)
            hits = np.flatnonzero((windows == pat).all(axis=1))
            # a hit must have a continuation: i + n < len (this also
            # excludes the suffix matching itself at i = len - n)
            hits = hits[hits + n < h.size]
            if hits.size:
                start = int(hits[-1]) + n  # most recent occurrence
                return h[start:start + k].copy()
        return _EMPTY
