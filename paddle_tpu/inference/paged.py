"""Paged KV cache (parity: the reference's decode-path cache machinery —
phi ``masked_multihead_attention`` / ``fused_multi_transformer``'s
contiguous per-sequence caches — upgraded to a vLLM-style page pool).

TPU-native design: XLA needs static shapes, so the pool is a fixed
tensor ``[kv_heads, n_pages, page_size, head_dim]`` per layer and the
indirection is data: a ``block_table`` [slots, max_pages] of page ids
and per-slot ``seq_lens``. Gathers over the page axis compile to
efficient dynamic-gathers; no recompilation as sequences come and go.
The pool is HEAD-MAJOR: one (head, page) block is contiguous with minor
dims (page_size, head_dim), which is what the Pallas decode kernel's
per-step DMA needs (TPU tiles the last two dims — a head-minor pool
would make the per-head slice strided and un-lowerable), and it puts
the tensor-parallel sharding axis (kv heads) first.
The win over per-slot contiguous caches is oversubscription: the pool
holds ``n_pages × page_size`` tokens total, which can be far less than
``slots × max_len`` when sequence lengths vary — the same HBM savings
that motivate paging on GPUs, but with the block-table gather living
inside one jitted decode program.

Page allocation (free-list) is host-side bookkeeping in the engine —
it's O(requests), not O(tokens), and never enters the compiled program.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class PagedLayerCache(NamedTuple):
    """Per-layer page pool + indirection (all device arrays).

    ``k_scale``/``v_scale`` are present only for int8 pools: per-ROW
    f32 dequant scales laid out ``[kv_heads, n_pages, page_size, 1]``
    so a page's scale rows travel WITH the page — adopt/COW/evict are
    page-id bookkeeping, and the scale arrays are indexed by the same
    page ids, so prefix sharing and rollback carry quantization state
    for free. The trailing 1 keeps the scale blocks the same
    (sublane, lane)-shaped as the pool blocks the Pallas decode kernel
    already streams (page_size × d with d→1)."""

    k_pages: jax.Array  # [kv_heads, n_pages, page_size, head_dim]
    v_pages: jax.Array  # [kv_heads, n_pages, page_size, head_dim]
    k_scale: Optional[jax.Array] = None  # [kv_heads, n_pages, page_size, 1]
    v_scale: Optional[jax.Array] = None


class PagedState(NamedTuple):
    """Cross-layer decode state carried through the jitted step."""

    block_tables: jax.Array  # [slots, max_pages] int32 page ids
    seq_lens: jax.Array  # [slots] int32 — tokens already in cache


class QuantizedKV(NamedTuple):
    """int8 CONTIGUOUS cache side (K or V): payload + per-row scales.

    q: [slots, max_len, kv_heads, head_dim] int8;
    scale: [slots, max_len, kv_heads] f32 — one symmetric absmax scale
    per written row per head (the "block row" granularity: dequant is
    ``q * scale[..., None]``). Drop-in for the plain array in the
    engine's per-layer ``(K, V)`` tuples — ``shape``/``dtype`` mirror
    the payload so shape-derived dispatch (chunk length, fused-kernel
    gating) keeps working."""

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


# one eps for every int8-KV quantization site — the kernels own it,
# this module's XLA append paths import it (see the constant's note
# in kernels/paged_attention.py)
from ..kernels.paged_attention import KV_QUANT_EPS  # noqa: E402


def quantize_kv_rows(x, out_dtype=jnp.int8):
    """Symmetric per-row int8 over the LAST axis: x [..., d] →
    (q int8 [..., d], scale f32 [...]). THE quantization rule for every
    KV append path — host XLA scatters and the fused Pallas kernels
    share the same math (absmax/127, round, clip) so fused and unfused
    engines write bit-identical pools."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / 127.0, KV_QUANT_EPS)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127) \
        .astype(out_dtype)
    return q, scale


def dequantize_kv(c):
    """QuantizedKV (or raw array) → f32 values."""
    if isinstance(c, QuantizedKV):
        return c.q.astype(jnp.float32) * c.scale[..., None]
    return c


def init_paged_pool(n_layers: int, n_pages: int, page_size: int,
                    kv_heads: int, head_dim: int, dtype=jnp.bfloat16):
    """int8 ``dtype`` builds quantized pools with per-row scale arrays
    alongside (zero-init: q=0 × scale=0 dequantizes to the same zeros a
    fp pool starts with; every read row is appended first)."""
    quant = jnp.dtype(dtype) == jnp.int8

    def one():
        pages = jnp.zeros((kv_heads, n_pages, page_size, head_dim),
                          dtype)
        scale = (jnp.zeros((kv_heads, n_pages, page_size, 1),
                           jnp.float32) if quant else None)
        return pages, scale

    out = []
    for _ in range(n_layers):
        kp, ks = one()
        vp, vs = one()
        out.append(PagedLayerCache(kp, vp, ks, vs))
    return out


def append_kv(cache: PagedLayerCache, state: PagedState, k, v
              ) -> PagedLayerCache:
    """Write one token's K/V per slot at its current length.

    k, v: [slots, 1, kv_heads, head_dim]. The destination of slot i is
    page ``block_tables[i, len_i // page_size]`` offset ``len_i %
    page_size`` — a scatter with computed indices, fully inside jit.
    """
    page_size = cache.k_pages.shape[2]
    slots = k.shape[0]
    lens = state.seq_lens
    page_idx = lens // page_size
    offs = lens % page_size
    pages = state.block_tables[jnp.arange(slots), page_idx]  # [slots]
    if cache.k_scale is not None:
        # quantize-on-append: the row's int8 payload and its f32 scale
        # land at the SAME (page, offset) — the scale rides the page
        kq, ks = quantize_kv_rows(k[:, 0])  # [slots, kvh, d] / [s, kvh]
        vq, vs = quantize_kv_rows(v[:, 0])
        return cache._replace(
            k_pages=cache.k_pages.at[:, pages, offs].set(
                kq.transpose(1, 0, 2)),
            v_pages=cache.v_pages.at[:, pages, offs].set(
                vq.transpose(1, 0, 2)),
            k_scale=cache.k_scale.at[:, pages, offs, 0].set(
                ks.transpose(1, 0)),
            v_scale=cache.v_scale.at[:, pages, offs, 0].set(
                vs.transpose(1, 0)),
        )
    # destination [kvh, pages[i], offs[i]] <- k[i, 0, h]: value laid out
    # head-major to match the pool
    k_pages = cache.k_pages.at[:, pages, offs].set(
        k[:, 0].astype(cache.k_pages.dtype).transpose(1, 0, 2))
    v_pages = cache.v_pages.at[:, pages, offs].set(
        v[:, 0].astype(cache.v_pages.dtype).transpose(1, 0, 2))
    return cache._replace(k_pages=k_pages, v_pages=v_pages)


def append_kv_chunk(cache: PagedLayerCache, state: PagedState, k, v,
                    start) -> PagedLayerCache:
    """Write a CHUNK of tokens per slot through the block table.

    k, v: [slots, s, kv_heads, head_dim]; ``start``: [slots] int32 —
    slot i's rows land at positions ``start[i] .. start[i]+s-1`` (page
    ``block_tables[i, pos // page_size]`` offset ``pos % page_size``).
    Positions past the block table's span (including the engine's
    ``start = max_len`` "not prefilling this call" sentinel) scatter
    with ``mode="drop"`` — a dropped write, never a clamped one.
    """
    page_size = cache.k_pages.shape[2]
    slots, s = k.shape[0], k.shape[1]
    max_pages = state.block_tables.shape[1]
    n_pages = cache.k_pages.shape[1]
    pos = start[:, None] + jnp.arange(s, dtype=start.dtype)[None, :]
    page_idx = pos // page_size
    offs = pos % page_size
    valid = page_idx < max_pages
    safe = jnp.minimum(page_idx, max_pages - 1)
    pages = jnp.take_along_axis(state.block_tables, safe, axis=1)
    pages = jnp.where(valid, pages, n_pages)  # OOB page id -> dropped
    if cache.k_scale is not None:
        kq, ks = quantize_kv_rows(k)  # [slots, s, kvh, d] / [slots, s, kvh]
        vq, vs = quantize_kv_rows(v)
        return cache._replace(
            k_pages=cache.k_pages.at[:, pages, offs].set(
                kq.transpose(2, 0, 1, 3), mode="drop"),
            v_pages=cache.v_pages.at[:, pages, offs].set(
                vq.transpose(2, 0, 1, 3), mode="drop"),
            k_scale=cache.k_scale.at[:, pages, offs, 0].set(
                ks.transpose(2, 0, 1), mode="drop"),
            v_scale=cache.v_scale.at[:, pages, offs, 0].set(
                vs.transpose(2, 0, 1), mode="drop"),
        )
    # value laid out head-major to match the pool: [kvh, slots, s, d]
    k_pages = cache.k_pages.at[:, pages, offs].set(
        k.astype(cache.k_pages.dtype).transpose(2, 0, 1, 3), mode="drop")
    v_pages = cache.v_pages.at[:, pages, offs].set(
        v.astype(cache.v_pages.dtype).transpose(2, 0, 1, 3), mode="drop")
    return cache._replace(k_pages=k_pages, v_pages=v_pages)


def gather_kv(cache: PagedLayerCache, state: PagedState
              ) -> Tuple[jax.Array, jax.Array]:
    """Materialize each slot's logical KV view: [slots, max_ctx, kvh, d]
    where max_ctx = max_pages * page_size (mask handles the tail).
    int8 pools are DEQUANTIZED in the gather (q × per-row scale), so
    every downstream consumer sees f32 values."""
    bt = state.block_tables  # [slots, max_pages]
    slots, max_pages = bt.shape
    kvh, _, page_size, d = cache.k_pages.shape
    k = cache.k_pages[:, bt]  # [kvh, slots, max_pages, page_size, d]
    v = cache.v_pages[:, bt]
    if cache.k_scale is not None:
        k = k.astype(jnp.float32) * cache.k_scale[:, bt]
        v = v.astype(jnp.float32) * cache.v_scale[:, bt]
    k = k.reshape(kvh, slots, max_pages * page_size, d)
    v = v.reshape(kvh, slots, max_pages * page_size, d)
    return (k.transpose(1, 2, 0, 3), v.transpose(1, 2, 0, 3))


def _use_pallas_decode(cache: PagedLayerCache) -> bool:
    import os

    import jax as _jax

    from ..kernels.decode_attention import decode_tiles_ok

    if cache.k_scale is not None:
        # int8 pools: the plain (non-fused) block-table kernel has no
        # dequant path — the FUSED kernel is the int8 production path,
        # and this dispatch's fallback is the dense dequant reference
        return False
    page_size, d = cache.k_pages.shape[2], cache.k_pages.shape[3]
    aligned = decode_tiles_ok(d, page_size)
    if os.environ.get("PADDLE_TPU_FORCE_PALLAS"):
        return aligned
    return aligned and _jax.default_backend() == "tpu"


def paged_attention(q, cache: PagedLayerCache, state: PagedState,
                    scale=None):
    """Decode attention over the paged cache.

    q: [slots, 1, heads, head_dim] (GQA: heads a multiple of kv_heads).
    The current token's K/V must already be appended, so slot i attends
    to positions [0, seq_lens[i]] inclusive of itself.
    Returns [slots, 1, heads, head_dim].

    On TPU this runs the Pallas block-table kernel
    (kernels/paged_attention.py): pages stream straight from the pool by
    page id — per-step HBM traffic ∝ Σ seq_lens rather than the
    slots × max_ctx of the dense gather fallback below.
    """
    slots, one, h, d = q.shape
    kvh_ = cache.k_pages.shape[0]
    if _use_pallas_decode(cache) and h % kvh_ == 0:
        from ..kernels.paged_attention import paged_decode_attention

        qg = q[:, 0].reshape(slots, kvh_, h // kvh_, d)
        out = paged_decode_attention(
            qg, cache.k_pages, cache.v_pages, state.block_tables,
            state.seq_lens, scale=scale,
        )
        return out.reshape(slots, 1, h, d)
    return dense_paged_attention(q, cache, state, scale=scale)


def dense_paged_attention(q, cache: PagedLayerCache, state: PagedState,
                          scale=None):
    """Dense-gather decode fallback (and the kernels' numeric reference):
    materializes each slot's full [max_ctx] view and masks — the
    slots × max_len traffic the Pallas paths avoid."""
    slots, one, h, d = q.shape
    k, v = gather_kv(cache, state)  # [slots, ctx, kvh, d]
    ctx = k.shape[1]
    kvh = k.shape[2]
    if h != kvh:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32) * scale
    # [slots, h, 1, ctx]
    s = jnp.einsum("sqhd,skhd->shqk", qf, k.astype(jnp.float32))
    mask = jnp.arange(ctx)[None, :] <= state.seq_lens[:, None]  # [slots,ctx]
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shqk,skhd->sqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


class PagePool:
    """Host-side page allocator (free list) + device state mirror.

    The engine calls ``alloc``/``free`` as requests arrive/finish and
    pushes the updated block table to the device as plain int32 data —
    allocation never triggers recompilation.

    Pages carry REFCOUNTS so a prefix cache can share them: ``ref[p]``
    counts owners (each slot holding p in its block table, plus the
    prefix store if it retains p). A page returns to the free list only
    at refcount 0; a slot must never write a page with refcount > 1 —
    the engine copies it first (``cow``).
    """

    def __init__(self, n_pages: int, page_size: int, slots: int,
                 max_pages_per_slot: int, reserve_sink: bool = False):
        """``reserve_sink``: keep page 0 out of circulation as a write
        sink for inactive slots (their block tables point at it)."""
        self.n_pages = n_pages
        self.page_size = page_size
        self.slots = slots
        self.max_pages_per_slot = max_pages_per_slot
        # recorded for the invariant sanitizer: the sink page must
        # never re-enter circulation
        self.reserve_sink = reserve_sink
        first = 1 if reserve_sink else 0
        self._free = list(range(n_pages - 1, first - 1, -1))
        self.block_tables = np.zeros((slots, max_pages_per_slot), np.int32)
        self.pages_of: dict = {i: [] for i in range(slots)}
        self.ref: dict = {}  # page id -> owner count (absent == 0)
        # pages with ref > 1 — lets the engine's decode-time COW guard
        # skip its per-slot scan when NOTHING is shared (prefix-cache
        # off, or no request published blocks yet). With the cache on
        # and warm, published prompt blocks keep this > 0, and the
        # guard pays its window-bounded scan (a couple of dict lookups
        # per active slot per dispatch)
        self.shared_pages = 0

    def _bump(self, page: int):
        n = self.ref.get(page, 0) + 1
        self.ref[page] = n
        if n == 2:
            self.shared_pages += 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def alloc(self, slot: int, n_tokens: int) -> bool:
        """Ensure slot has pages for n_tokens total; False if pool full."""
        have = len(self.pages_of[slot])
        need = self.pages_needed(n_tokens) - have
        if need > len(self._free) or \
                have + max(need, 0) > self.max_pages_per_slot:
            return False
        for _ in range(max(need, 0)):
            p = self._free.pop()
            self.block_tables[slot, len(self.pages_of[slot])] = p
            self.pages_of[slot].append(p)
            self.ref[p] = 1
        return True

    def adopt(self, slot: int, pages) -> bool:
        """Prefix-share: place already-populated ``pages`` at the FRONT
        of an empty slot's block table (refcount + 1 each) — the caller
        tops the rest up with ``alloc``. False if the list alone would
        exceed the per-slot maximum (nothing adopted)."""
        if self.pages_of[slot]:
            raise ValueError(f"adopt() needs an empty slot; slot {slot} "
                             f"holds {len(self.pages_of[slot])} pages")
        if len(pages) > self.max_pages_per_slot:
            return False
        for p in pages:
            self.block_tables[slot, len(self.pages_of[slot])] = p
            self.pages_of[slot].append(p)
            self._bump(p)
        return True

    def retain(self, page: int):
        """Add an owner (the prefix store pinning a page)."""
        self._bump(page)

    def release(self, page: int):
        """Drop an owner; the page frees at refcount 0. Releasing an
        un-owned page is a double-free — loud, because the silent
        version hands one page to two slots later."""
        was = self.ref.get(page, 0)
        if was <= 0:
            raise ValueError(f"release() of un-owned page {page}")
        if was == 2:
            self.shared_pages -= 1
        if was == 1:
            self.ref.pop(page, None)
            self._free.append(page)
        else:
            self.ref[page] = was - 1

    def cow(self, slot: int, block_idx: int) -> Optional[int]:
        """Copy-on-write bookkeeping: swap the (shared) page at
        ``block_idx`` of this slot for a fresh private one. Returns the
        new page id (the CALLER must device-copy old → new before any
        write), or None when the free list is empty."""
        if not self._free:
            return None
        old = self.pages_of[slot][block_idx]
        new = self._free.pop()
        self.pages_of[slot][block_idx] = new
        self.block_tables[slot, block_idx] = new
        self.ref[new] = 1
        self.release(old)
        return new

    def free(self, slot: int):
        for p in reversed(self.pages_of[slot]):
            self.release(p)
        self.pages_of[slot] = []
        self.block_tables[slot] = 0

    def device_state(self, seq_lens: np.ndarray) -> PagedState:
        return PagedState(
            block_tables=jnp.asarray(self.block_tables),
            seq_lens=jnp.asarray(seq_lens, jnp.int32),
        )
