"""Paged KV cache (parity: the reference's decode-path cache machinery —
phi ``masked_multihead_attention`` / ``fused_multi_transformer``'s
contiguous per-sequence caches — upgraded to a vLLM-style page pool).

TPU-native design: XLA needs static shapes, so the pool is a fixed
tensor ``[kv_heads, n_pages, page_size, head_dim]`` per layer and the
indirection is data: a ``block_table`` [slots, max_pages] of page ids
and per-slot ``seq_lens``. Gathers over the page axis compile to
efficient dynamic-gathers; no recompilation as sequences come and go.
The pool is HEAD-MAJOR: one (head, page) block is contiguous with minor
dims (page_size, head_dim), which is what the Pallas decode kernel's
per-step DMA needs (TPU tiles the last two dims — a head-minor pool
would make the per-head slice strided and un-lowerable), and it puts
the tensor-parallel sharding axis (kv heads) first.
The win over per-slot contiguous caches is oversubscription: the pool
holds ``n_pages × page_size`` tokens total, which can be far less than
``slots × max_len`` when sequence lengths vary — the same HBM savings
that motivate paging on GPUs, but with the block-table gather living
inside one jitted decode program.

Page allocation (free-list) is host-side bookkeeping in the engine —
it's O(requests), not O(tokens), and never enters the compiled program.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class PagedLayerCache(NamedTuple):
    """Per-layer page pool + indirection (all device arrays)."""

    k_pages: jax.Array  # [kv_heads, n_pages, page_size, head_dim]
    v_pages: jax.Array  # [kv_heads, n_pages, page_size, head_dim]


class PagedState(NamedTuple):
    """Cross-layer decode state carried through the jitted step."""

    block_tables: jax.Array  # [slots, max_pages] int32 page ids
    seq_lens: jax.Array  # [slots] int32 — tokens already in cache


def init_paged_pool(n_layers: int, n_pages: int, page_size: int,
                    kv_heads: int, head_dim: int, dtype=jnp.bfloat16):
    return [
        PagedLayerCache(
            k_pages=jnp.zeros((kv_heads, n_pages, page_size, head_dim),
                              dtype),
            v_pages=jnp.zeros((kv_heads, n_pages, page_size, head_dim),
                              dtype),
        )
        for _ in range(n_layers)
    ]


def append_kv(cache: PagedLayerCache, state: PagedState, k, v
              ) -> PagedLayerCache:
    """Write one token's K/V per slot at its current length.

    k, v: [slots, 1, kv_heads, head_dim]. The destination of slot i is
    page ``block_tables[i, len_i // page_size]`` offset ``len_i %
    page_size`` — a scatter with computed indices, fully inside jit.
    """
    page_size = cache.k_pages.shape[2]
    slots = k.shape[0]
    lens = state.seq_lens
    page_idx = lens // page_size
    offs = lens % page_size
    pages = state.block_tables[jnp.arange(slots), page_idx]  # [slots]
    # destination [kvh, pages[i], offs[i]] <- k[i, 0, h]: value laid out
    # head-major to match the pool
    k_pages = cache.k_pages.at[:, pages, offs].set(
        k[:, 0].astype(cache.k_pages.dtype).transpose(1, 0, 2))
    v_pages = cache.v_pages.at[:, pages, offs].set(
        v[:, 0].astype(cache.v_pages.dtype).transpose(1, 0, 2))
    return PagedLayerCache(k_pages, v_pages)


def gather_kv(cache: PagedLayerCache, state: PagedState
              ) -> Tuple[jax.Array, jax.Array]:
    """Materialize each slot's logical KV view: [slots, max_ctx, kvh, d]
    where max_ctx = max_pages * page_size (mask handles the tail)."""
    bt = state.block_tables  # [slots, max_pages]
    slots, max_pages = bt.shape
    kvh, _, page_size, d = cache.k_pages.shape
    k = cache.k_pages[:, bt]  # [kvh, slots, max_pages, page_size, d]
    v = cache.v_pages[:, bt]
    k = k.reshape(kvh, slots, max_pages * page_size, d)
    v = v.reshape(kvh, slots, max_pages * page_size, d)
    return (k.transpose(1, 2, 0, 3), v.transpose(1, 2, 0, 3))


def _use_pallas_decode(cache: PagedLayerCache) -> bool:
    import os

    import jax as _jax

    from ..kernels.decode_attention import decode_tiles_ok

    page_size, d = cache.k_pages.shape[2], cache.k_pages.shape[3]
    aligned = decode_tiles_ok(d, page_size)
    if os.environ.get("PADDLE_TPU_FORCE_PALLAS"):
        return aligned
    return aligned and _jax.default_backend() == "tpu"


def paged_attention(q, cache: PagedLayerCache, state: PagedState,
                    scale=None):
    """Decode attention over the paged cache.

    q: [slots, 1, heads, head_dim] (GQA: heads a multiple of kv_heads).
    The current token's K/V must already be appended, so slot i attends
    to positions [0, seq_lens[i]] inclusive of itself.
    Returns [slots, 1, heads, head_dim].

    On TPU this runs the Pallas block-table kernel
    (kernels/paged_attention.py): pages stream straight from the pool by
    page id — per-step HBM traffic ∝ Σ seq_lens rather than the
    slots × max_ctx of the dense gather fallback below.
    """
    slots, one, h, d = q.shape
    kvh_ = cache.k_pages.shape[0]
    if _use_pallas_decode(cache) and h % kvh_ == 0:
        from ..kernels.paged_attention import paged_decode_attention

        qg = q[:, 0].reshape(slots, kvh_, h // kvh_, d)
        out = paged_decode_attention(
            qg, cache.k_pages, cache.v_pages, state.block_tables,
            state.seq_lens, scale=scale,
        )
        return out.reshape(slots, 1, h, d)
    return dense_paged_attention(q, cache, state, scale=scale)


def dense_paged_attention(q, cache: PagedLayerCache, state: PagedState,
                          scale=None):
    """Dense-gather decode fallback (and the kernels' numeric reference):
    materializes each slot's full [max_ctx] view and masks — the
    slots × max_len traffic the Pallas paths avoid."""
    slots, one, h, d = q.shape
    k, v = gather_kv(cache, state)  # [slots, ctx, kvh, d]
    ctx = k.shape[1]
    kvh = k.shape[2]
    if h != kvh:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32) * scale
    # [slots, h, 1, ctx]
    s = jnp.einsum("sqhd,skhd->shqk", qf, k.astype(jnp.float32))
    mask = jnp.arange(ctx)[None, :] <= state.seq_lens[:, None]  # [slots,ctx]
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shqk,skhd->sqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


class PagePool:
    """Host-side page allocator (free list) + device state mirror.

    The engine calls ``alloc``/``free`` as requests arrive/finish and
    pushes the updated block table to the device as plain int32 data —
    allocation never triggers recompilation.
    """

    def __init__(self, n_pages: int, page_size: int, slots: int,
                 max_pages_per_slot: int, reserve_sink: bool = False):
        """``reserve_sink``: keep page 0 out of circulation as a write
        sink for inactive slots (their block tables point at it)."""
        self.n_pages = n_pages
        self.page_size = page_size
        self.slots = slots
        self.max_pages_per_slot = max_pages_per_slot
        first = 1 if reserve_sink else 0
        self._free = list(range(n_pages - 1, first - 1, -1))
        self.block_tables = np.zeros((slots, max_pages_per_slot), np.int32)
        self.pages_of: dict = {i: [] for i in range(slots)}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def alloc(self, slot: int, n_tokens: int) -> bool:
        """Ensure slot has pages for n_tokens total; False if pool full."""
        have = len(self.pages_of[slot])
        need = self.pages_needed(n_tokens) - have
        if need > len(self._free) or \
                have + max(need, 0) > self.max_pages_per_slot:
            return False
        for _ in range(max(need, 0)):
            p = self._free.pop()
            self.block_tables[slot, len(self.pages_of[slot])] = p
            self.pages_of[slot].append(p)
        return True

    def free(self, slot: int):
        self._free.extend(reversed(self.pages_of[slot]))
        self.pages_of[slot] = []
        self.block_tables[slot] = 0

    def device_state(self, seq_lens: np.ndarray) -> PagedState:
        return PagedState(
            block_tables=jnp.asarray(self.block_tables),
            seq_lens=jnp.asarray(seq_lens, jnp.int32),
        )
