"""paddle_tpu.device (parity: paddle.device — set_device/get_device and
the synchronization/stream surface; python/paddle/device/__init__.py).

Device identity on TPU is owned by PJRT; "streams" are XLA's async
dispatch queue, so ``synchronize`` maps to blocking on all live arrays
(the effective barrier jax exposes)."""

from __future__ import annotations

import jax

_current = None


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def set_device(device: str):
    """Parity: paddle.device.set_device('gpu:0'|'cpu'|...). Maps device
    kinds onto the jax default-device mechanism."""
    global _current
    plat = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    alias = {"gpu": "tpu", "xpu": "tpu", "npu": "tpu"}.get(plat, plat)
    try:
        # query the named backend directly — jax.devices() alone only
        # lists the default backend, which would silently misroute e.g.
        # set_device("cpu") on a TPU host
        matches = list(jax.devices(alias))
    except RuntimeError as e:
        raise ValueError(
            f"set_device: no {device!r} backend available") from e
    if not 0 <= idx < len(matches):
        raise ValueError(
            f"set_device: index {idx} out of range for "
            f"{len(matches)} {alias} device(s)")
    jax.config.update("jax_default_device", matches[idx])
    _current = device
    return device


def get_device():
    if _current is not None:
        return _current
    d = jax.devices()[0]
    name = {"tpu": "gpu"}.get(d.platform, d.platform)  # paddle alias
    return f"{name}:{d.id}"


def synchronize(device=None):
    """Block until all dispatched work completes on EVERY device
    (parity: paddle.device.synchronize / cuda.synchronize)."""
    for d in jax.devices():
        (jax.device_put(0.0, d) + 0).block_until_ready()


def device_count():
    return len(jax.devices())


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(name: str = "tpu"):
    return any(d.platform == name for d in jax.devices())


class Stream:
    """Parity shim: XLA owns scheduling; stream objects are inert
    markers (documented N/A — one async queue per device)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)
