"""Compat shims over jax API drift between the 0.4.x and 0.5+ lines.

The repo targets current jax (``jax.shard_map`` with ``check_vma``),
but must also import cleanly on 0.4.x containers where shard_map lives
in ``jax.experimental.shard_map`` and the replication-check kwarg is
still called ``check_rep``. Every internal user imports shard_map from
here instead of from jax directly.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5 exposes it at top level
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)
_ACCEPTS_VMA = "check_vma" in _PARAMS
_ACCEPTS_AXIS_NAMES = "axis_names" in _PARAMS


def shard_map(f, *args, **kw):
    if not _ACCEPTS_VMA and "check_vma" in kw:
        kw["check_rep"] = kw.pop("check_vma")
    if not _ACCEPTS_AXIS_NAMES and "axis_names" in kw:
        # 0.4.x has no axis_names; run fully manual instead. The
        # equivalent `auto=complement` translation CHECK-crashes 0.4.37's
        # XLA on some programs (dropless-EP ragged_dot under a partial-
        # auto shard_map), and these callers set check_vma/check_rep
        # False anyway — unnamed axes just see replicated shards, which
        # is semantically identical and only costs an extra gather on
        # the old-jax CPU test path, never on the prod (new-jax) path.
        kw.pop("axis_names")
    return _shard_map(f, *args, **kw)


try:  # jax >= 0.5: top-level context manager
    from jax import enable_x64  # noqa: F401
except ImportError:  # jax 0.4.x
    from jax.experimental import enable_x64  # noqa: F401


def pcast(x, axes, to="varying"):
    """``jax.lax.pcast`` when it exists (new jax's varying-manual-axes
    bookkeeping inside shard_map); identity on 0.4.x, where the vma
    concept — and therefore the cast — does not exist."""
    import jax

    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axes, to=to)


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` (new jax) / ``TPUCompilerParams``
    (0.4.x) — one shim so Pallas kernels don't each carry the rename."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or \
        pltpu.TPUCompilerParams
    return cls(**kw)


def vma_of(x):
    """The varying-manual-axes set of ``x``'s type (new jax); empty set
    on 0.4.x, which has neither ``jax.typeof`` nor vma tracking."""
    import jax

    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(x), "vma", frozenset())
