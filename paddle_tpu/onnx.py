"""paddle.onnx namespace (parity: python/paddle/onnx/__init__.py).

ONNX is a CUDA-ecosystem interchange format; the TPU-native export
path is StableHLO via ``paddle_tpu.jit.save`` (portable, versioned,
loadable by jax.export everywhere — see MAPPING.md "ONNX export").
``export`` raises with that pointer instead of silently writing a file
other TPU tooling could not consume.
"""

from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is N/A on the TPU stack (see MAPPING.md): the "
        "portable export format here is StableHLO — use "
        "paddle_tpu.jit.save(layer, path, input_spec) and load with "
        "paddle_tpu.jit.load / jax.export on any jax platform")
