"""QAT / PTQ workflows (parity: python/paddle/quantization/{qat,ptq}.py,
QuantConfig in python/paddle/quantization/config.py).

Usage parity with the reference:

    q_config = QuantConfig(activation=FakeQuant(bits=8), weight=...)
    qat = QAT(q_config)
    qmodel = qat.quantize(model)        # Linear → QuantedLinear (STE)
    ... train ...
    infer_model = qat.convert(qmodel)   # → WeightOnlyLinear (int8)

    ptq = PTQ(QuantConfig(activation=AbsmaxObserver))
    pmodel = ptq.quantize(model)        # insert observers
    for batch in calib: pmodel(batch)
    infer_model = ptq.convert(pmodel)
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, Optional, Type

from ..core.module import Layer
from ..nn.layer.common import Linear
from .observer import AbsmaxObserver, BaseObserver


class _Unset:
    def __repr__(self):
        return "<UNSET>"


UNSET = _Unset()


class QuantConfig:
    """Which layers get quantized and with what quanter/observer.

    ``activation`` / ``weight`` accept a factory (class / zero-arg
    callable) or a template quanter *instance* (deep-copied per
    instrumented layer so statistics are never shared). Explicit ``None``
    means "leave unquantized" (reference semantics); leaving an override
    field unset inherits the global setting.
    """

    def __init__(self, activation=UNSET, weight=UNSET):
        self.activation = activation
        self.weight = weight
        self._layer_overrides: Dict[int, dict] = {}
        self._type_overrides: Dict[Type, dict] = {}

    def add_layer_config(self, layer, activation=UNSET, weight=UNSET):
        for lyr in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_overrides[id(lyr)] = {
                "activation": activation, "weight": weight}
        return self

    def add_type_config(self, layer_type, activation=UNSET, weight=UNSET):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_overrides[t] = {
                "activation": activation, "weight": weight}
        return self

    def _for(self, layer) -> dict:
        override = self._layer_overrides.get(id(layer)) or \
            self._type_overrides.get(type(layer)) or {}
        out = {"activation": self.activation, "weight": self.weight}
        for k, v in override.items():
            if v is not UNSET:
                out[k] = v
        return out

    @staticmethod
    def _make(factory, default=None):
        """UNSET → default; None → None (disabled); Layer instance →
        per-layer deep copy; class/callable → call it."""
        if factory is UNSET:
            factory = default
        if factory is None:
            return None
        if isinstance(factory, Layer):
            return copy.deepcopy(factory)
        return factory() if callable(factory) else factory


class QuantedLinear(Layer):
    """Linear with fake-quant on activations and weights (QAT training)."""

    def __init__(self, linear: Linear, act_quanter=None, wt_quanter=None):
        super().__init__()
        self.source = linear
        self.act_quanter = act_quanter
        self.wt_quanter = wt_quanter

    def forward(self, x):
        import jax.numpy as jnp

        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self.source.weight.value
        if self.wt_quanter is not None:
            w = self.wt_quanter(w)
        y = jnp.matmul(x, w.astype(x.dtype))
        if self.source.bias is not None:
            y = y + self.source.bias.value.astype(y.dtype)
        return y


def replace_layers(model: Layer, match: Callable[[Layer], bool],
                   make: Callable[[Layer], Layer]) -> Layer:
    """Swap every sublayer where ``match`` holds with ``make(sub)`` —
    the single tree-mutation walk all quantize/convert passes share."""
    for parent in model.sublayers(include_self=True):
        for name, sub in list(parent._sub_layers.items()):
            if match(sub):
                parent._sub_layers[name] = make(sub)
    return model


class QAT:
    """Quantization-aware training driver (parity: paddle.quantization.QAT)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        def make(linear):
            from . import FakeQuant

            cfg = self.config._for(linear)
            act = QuantConfig._make(cfg["activation"], default=FakeQuant)
            wt = QuantConfig._make(cfg["weight"], default=FakeQuant)
            if act is None and wt is None:
                return linear  # explicitly disabled for this layer
            return QuantedLinear(linear, act, wt)

        return replace_layers(model, lambda s: type(s) is Linear, make)

    def convert(self, model: Layer, inplace: bool = True,
                weight_dtype: str = "int8") -> Layer:
        """Strip quanters; emit WeightOnlyLinear for deployment."""
        from . import WeightOnlyLinear

        if not inplace:
            model = copy.deepcopy(model)
        return replace_layers(
            model, lambda s: isinstance(s, QuantedLinear),
            lambda s: WeightOnlyLinear(s.source, weight_dtype=weight_dtype))


class PTQ:
    """Post-training quantization driver (parity: paddle.quantization.PTQ).

    ``quantize`` inserts activation observers in front of each Linear;
    run calibration batches through the model eagerly; ``convert``
    replaces the pairs with WeightOnlyLinear whose *activation scale* is
    stored for downstream use (weight scales are computed from weights
    directly, matching the reference's weight-only PTQ path).
    """

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig(activation=AbsmaxObserver)

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        def make(linear):
            cfg = self.config._for(linear)
            obs = QuantConfig._make(cfg["activation"], default=AbsmaxObserver)
            if obs is None:
                return linear
            return _ObservedLinear(linear, obs)

        return replace_layers(model, lambda s: type(s) is Linear, make)

    def convert(self, model: Layer, inplace: bool = True,
                weight_dtype: str = "int8") -> Layer:
        from . import WeightOnlyLinear

        if not inplace:
            model = copy.deepcopy(model)

        def make(sub):
            wol = WeightOnlyLinear(sub.source, weight_dtype=weight_dtype)
            # act_scale is a registered buffer, so this assignment routes
            # into _buffers and persists through state_dict
            wol.act_scale = sub.observer.scale()
            return wol

        return replace_layers(
            model, lambda s: isinstance(s, _ObservedLinear), make)


class _ObservedLinear(Layer):
    def __init__(self, linear: Linear, observer: BaseObserver):
        super().__init__()
        self.source = linear
        self.observer = observer

    def forward(self, x):
        self.observer.observe(x)
        return self.source(x)
