"""Quantization (parity: python/paddle/quantization/ — PTQ observers,
QAT fake-quant wrappers — and the phi ``weight_only_linear`` int8/int4
kernels used for LLM inference).

TPU-native: weight-only int8 keeps weights quantized in HBM (halving
weight bandwidth, the actual bottleneck of decode) and dequantizes in
registers fused into the matmul — XLA fuses the scale-multiply into the
dot; a Pallas blockwise-dequant matmul kernel is the planned upgrade for
int4 grouped scales.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.module import Layer
from ..core.parameter import Parameter
from ..nn import functional as F


def quantize_weight_int8(w: jax.Array, axis: int = 0):
    """Symmetric per-channel int8: returns (q, scale). axis = the
    *preserved* (output-channel) axis."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                   keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def weight_only_linear(x, qweight, scale, bias=None):
    """y = x @ dequant(qweight) (+ bias). qweight int8 [in, out], scale
    [1, out] (per-out-channel). Parity: phi weight_only_linear."""
    w = qweight.astype(x.dtype) * scale.astype(x.dtype)
    y = jnp.matmul(x, w)
    if bias is not None:
        y = y + bias
    return y


class WeightOnlyLinear(Layer):
    """Drop-in for nn.Linear with int8 weights (inference)."""

    def __init__(self, linear_or_in, out_features: Optional[int] = None):
        super().__init__()
        from ..nn.layer.common import Linear

        if isinstance(linear_or_in, Linear):
            src = linear_or_in
            q, s = quantize_weight_int8(src.weight.value, axis=1)
            self.in_features = src.in_features
            self.out_features = src.out_features
            bias = None if src.bias is None else src.bias.value
        else:
            self.in_features = linear_or_in
            self.out_features = out_features
            q = jnp.zeros((self.in_features, self.out_features), jnp.int8)
            s = jnp.ones((1, self.out_features), jnp.float32)
            bias = jnp.zeros((self.out_features,), jnp.float32)
        self.register_buffer("qweight", q)
        self.register_buffer("scale", s)
        if bias is not None:
            self.bias = Parameter(bias, trainable=False)
        else:
            self.bias = None

    def forward(self, x):
        return weight_only_linear(
            x, self._buffers["qweight"], self._buffers["scale"],
            None if self.bias is None else self.bias.value,
        )


class FakeQuant(Layer):
    """QAT fake-quant (uniform symmetric, straight-through estimator)."""

    def __init__(self, bits: int = 8, observer_momentum: float = 0.9):
        super().__init__()
        self.qmax = 2 ** (bits - 1) - 1
        self.momentum = observer_momentum
        self.register_buffer("amax", jnp.ones((), jnp.float32))

    def forward(self, x):
        import jax.core

        amax_obs = jnp.max(jnp.abs(x.astype(jnp.float32)))
        if not isinstance(amax_obs, jax.core.Tracer) and self.training:
            self._buffers["amax"] = (
                self.momentum * self._buffers["amax"]
                + (1 - self.momentum) * amax_obs
            )
        amax = jnp.where(
            self.training, jnp.maximum(amax_obs, 1e-8),
            jnp.maximum(self._buffers["amax"], 1e-8),
        )
        scale = amax / self.qmax
        q = jnp.clip(jnp.round(x / scale), -self.qmax, self.qmax) * scale
        # straight-through: forward q, backward identity
        return x + jax.lax.stop_gradient(q - x)


def quantize_model_weight_only(model: Layer) -> Layer:
    """Replace every nn.Linear in the tree with WeightOnlyLinear."""
    from ..nn.layer.common import Linear

    for parent in model.sublayers(include_self=True):
        for name, sub in list(parent._sub_layers.items()):
            if type(sub) is Linear:
                parent._sub_layers[name] = WeightOnlyLinear(sub)
    return model
