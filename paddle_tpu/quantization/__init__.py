"""Quantization (parity: python/paddle/quantization/ — PTQ observers,
QAT fake-quant wrappers — and the phi ``weight_only_linear`` int8/int4
kernels used for LLM inference).

TPU-native: weight-only int8 keeps weights quantized in HBM (halving
weight bandwidth, the actual bottleneck of decode) and dequantizes in
registers fused into the matmul — XLA fuses the scale-multiply into the
dot; a Pallas blockwise-dequant matmul kernel is the planned upgrade for
int4 grouped scales.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.module import Layer
from ..core.parameter import Parameter
from ..nn import functional as F


def quantize_weight_int8(w: jax.Array, axis: int = 0):
    """Symmetric per-channel int8: returns (q, scale). axis = the
    *preserved* (output-channel) axis."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                   keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def weight_only_linear(x, qweight, scale, bias=None, weight_dtype="int8",
                       group_size=None, use_pallas=False):
    """y = x @ dequant(qweight) (+ bias). Parity: phi weight_only_linear.

    Two scale layouts:
      - per-out-channel (the original int8 path): scale [1, out];
      - group-wise (``group_size`` set): scale [in // group_size, out],
        qweight int8 [in, out] or int4 packed [in // 2, out].
    ``use_pallas`` routes group-wise matmuls through the Pallas
    blockwise-dequant kernel (kernels/quant_matmul.py) when shapes tile.
    """
    if group_size is None:
        w = qweight.astype(x.dtype) * scale.astype(x.dtype)
        y = jnp.matmul(x, w)
    else:
        from ..kernels import quant_matmul as qmm

        lead = x.shape[:-1]
        x2 = x.reshape(-1, x.shape[-1])
        m, k = x2.shape
        n = qweight.shape[1]
        # decode batches are tiny (m = active slots); pad m up to a
        # Mosaic-legal tile instead of falling back to the XLA path —
        # XLA dequantizes the WHOLE weight per call, which forfeits the
        # int8 bandwidth saving that decode lives on
        # 128-granular above 256 keeps full MXU rows with <1 dead block
        m_pad = (-(-m // 16) * 16 if m <= 256 else -(-m // 128) * 128)
        m_block = min(256, m_pad) if m_pad % 256 == 0 or m_pad <= 256 \
            else 128
        tiles = (use_pallas and n % 256 == 0
                 and k % 256 == 0 and 256 % group_size == 0)
        if tiles:
            if m_pad != m:
                x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
            y = qmm.weight_only_matmul_pallas(
                x2, qweight, scale, group_size=group_size,
                weight_dtype=weight_dtype, m_block=m_block)
            if m_pad != m:
                y = y[:m]
        else:
            y = qmm.weight_only_matmul_xla(
                x2, qweight, scale, group_size=group_size,
                weight_dtype=weight_dtype)
        y = y.reshape(lead + (n,))
    if bias is not None:
        y = y + bias
    return y


class WeightOnlyLinear(Layer):
    """Drop-in for nn.Linear with int8/int4 weights (inference).

    ``weight_dtype='int4'`` packs two 4-bit values per byte with
    group-wise scales — weight HBM traffic drops 4x vs bf16, which is
    what decode latency buys from (see kernels/quant_matmul.py).
    """

    def __init__(self, linear_or_in, out_features: Optional[int] = None,
                 weight_dtype: str = "int8", group_size: Optional[int] = None,
                 use_pallas: bool = True):
        super().__init__()
        from ..kernels import quant_matmul as qmm
        from ..nn.layer.common import Linear

        self.weight_dtype = weight_dtype
        self.use_pallas = use_pallas
        if weight_dtype == "int4" and group_size is None:
            group_size = 128
        if not isinstance(linear_or_in, int):
            # any linear-shaped layer: nn.Linear or the TP variants
            # (Column/RowParallelLinear — quantized serving is a
            # single-chip path today, where their collectives are
            # identity)
            src = linear_or_in
            self.in_features = src.in_features
            self.out_features = src.out_features
            if group_size is not None and self.in_features % group_size:
                group_size = self.in_features  # degenerate single group
            w = src.weight.value
            if weight_dtype == "int4":
                q, s = qmm.quantize_weight_int4_grouped(w, group_size)
            elif group_size is not None:
                q, s = qmm.quantize_weight_int8_grouped(w, group_size)
            else:
                q, s = quantize_weight_int8(w, axis=1)
            bias = None if src.bias is None else src.bias.value
        else:
            self.in_features = linear_or_in
            self.out_features = out_features
            if group_size is not None and self.in_features % group_size:
                group_size = self.in_features  # degenerate single group
            if weight_dtype == "int4":
                if self.in_features % 2:
                    raise ValueError(
                        "int4 packing needs an even in_features; got "
                        f"{self.in_features}")
                q = jnp.zeros(
                    (self.in_features // 2, self.out_features), jnp.int8)
                s = jnp.ones((self.in_features // group_size,
                              self.out_features), jnp.float32)
            elif group_size is not None:
                q = jnp.zeros(
                    (self.in_features, self.out_features), jnp.int8)
                s = jnp.ones((self.in_features // group_size,
                              self.out_features), jnp.float32)
            else:
                q = jnp.zeros(
                    (self.in_features, self.out_features), jnp.int8)
                s = jnp.ones((1, self.out_features), jnp.float32)
            bias = jnp.zeros((self.out_features,), jnp.float32)
        self.group_size = group_size
        self.register_buffer("qweight", q)
        self.register_buffer("scale", s)
        # calibrated activation scale (filled by PTQ.convert; buffer so
        # it persists through state_dict)
        self.register_buffer("act_scale", jnp.zeros((), jnp.float32))
        if bias is not None:
            self.bias = Parameter(bias, trainable=False)
        else:
            self.bias = None

    def forward(self, x):
        return weight_only_linear(
            x, self._buffers["qweight"], self._buffers["scale"],
            None if self.bias is None else self.bias.value,
            weight_dtype=self.weight_dtype, group_size=self.group_size,
            use_pallas=self.use_pallas,
        )


class FakeQuant(Layer):
    """QAT fake-quant (uniform symmetric, straight-through estimator)."""

    def __init__(self, bits: int = 8, observer_momentum: float = 0.9):
        super().__init__()
        self.qmax = 2 ** (bits - 1) - 1
        self.momentum = observer_momentum
        self.register_buffer("amax", jnp.ones((), jnp.float32))

    def forward(self, x):
        import jax.core

        amax_obs = jnp.max(jnp.abs(x.astype(jnp.float32)))
        if not isinstance(amax_obs, jax.core.Tracer) and self.training:
            self._buffers["amax"] = (
                self.momentum * self._buffers["amax"]
                + (1 - self.momentum) * amax_obs
            )
        amax = jnp.where(
            self.training, jnp.maximum(amax_obs, 1e-8),
            jnp.maximum(self._buffers["amax"], 1e-8),
        )
        scale = amax / self.qmax
        q = jnp.clip(jnp.round(x / scale), -self.qmax, self.qmax) * scale
        # straight-through: forward q, backward identity
        return x + jax.lax.stop_gradient(q - x)


def quantize_model_weight_only(model: Layer, weight_dtype: str = "int8",
                               group_size: Optional[int] = None,
                               use_pallas: bool = True) -> Layer:
    """Replace every linear in the tree with WeightOnlyLinear.

    Matches nn.Linear AND the tensor-parallel variants
    (Column/RowParallelLinear) so transformer blocks built for the
    hybrid engine (e.g. models/llama.py) quantize too. Weight-only
    serving is a single-chip path today: at mesh size 1 the TP layers'
    collectives are identity, so swapping them for a dense quantized
    matmul is exact. (Parity: phi weight_only_linear serving kernels.)"""
    from ..distributed.parallel_layers.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
    )
    from ..nn.layer.common import Linear
    from .qat import replace_layers

    kinds = (Linear, ColumnParallelLinear, RowParallelLinear)
    return replace_layers(
        model, lambda s: type(s) in kinds,
        lambda s: WeightOnlyLinear(s, weight_dtype=weight_dtype,
                                   group_size=group_size,
                                   use_pallas=use_pallas))


from .observer import (  # noqa: E402,F401
    AbsmaxObserver,
    BaseObserver,
    EMAObserver,
    MSEObserver,
    PercentileObserver,
)
from .qat import PTQ, QAT, QuantConfig, QuantedLinear  # noqa: E402,F401
