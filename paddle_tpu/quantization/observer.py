"""Calibration observers (parity: python/paddle/quantization/observers/).

Observers watch activations/weights during PTQ calibration (eager, host
side — calibration is a few dozen batches, not a hot path) and produce
the quantization scale used at convert time.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.module import Layer


class BaseObserver(Layer):
    """Pass-through layer that records statistics of what flows through."""

    def forward(self, x):
        self.observe(x)
        return x

    def observe(self, x):
        raise NotImplementedError

    def scale(self, qmax: int = 127):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Running max of |x| (parity: AbsmaxObserver)."""

    def __init__(self):
        super().__init__()
        self._amax = 0.0

    def observe(self, x):
        self._amax = max(self._amax, float(jnp.max(jnp.abs(x))))

    def scale(self, qmax: int = 127):
        return max(self._amax, 1e-8) / qmax


class EMAObserver(BaseObserver):
    """Exponential moving average of per-batch absmax (parity:
    EMDObserver/AVGObserver family — smooths outlier batches)."""

    def __init__(self, momentum: float = 0.9):
        super().__init__()
        self.momentum = momentum
        self._amax = None

    def observe(self, x):
        amax = float(jnp.max(jnp.abs(x)))
        self._amax = amax if self._amax is None else (
            self.momentum * self._amax + (1 - self.momentum) * amax)

    def scale(self, qmax: int = 127):
        return max(self._amax or 0.0, 1e-8) / qmax


class PercentileObserver(BaseObserver):
    """Clips to the p-th percentile of |x| samples (parity:
    HistObserver/KL-based observers' role: outlier-robust range)."""

    def __init__(self, percentile: float = 99.9, max_samples: int = 1 << 18):
        super().__init__()
        self.percentile = percentile
        self.max_samples = max_samples
        # fixed-size reservoir: memory stays O(max_samples) total no
        # matter how many calibration batches flow through
        self._reservoir = np.empty((0,), np.float32)
        self._seen = 0
        self._rng = np.random.default_rng(0)

    def observe(self, x):
        flat = np.abs(np.asarray(x, dtype=np.float32)).ravel()
        self._seen += flat.size
        room = self.max_samples - self._reservoir.size
        if room > 0:
            take = flat[:room]
            self._reservoir = np.concatenate([self._reservoir, take])
            flat = flat[room:]
        if flat.size:
            # reservoir admission: each new value replaces w.p.
            # max_samples/seen — no minimum, or the reservoir would
            # converge to just the most recent batches
            n_rep = min(flat.size,
                        int(self.max_samples * flat.size / self._seen))
            if n_rep:
                idx = self._rng.choice(self.max_samples, n_rep,
                                       replace=False)
                src = self._rng.choice(flat.size, n_rep, replace=False)
                self._reservoir[idx] = flat[src]

    def scale(self, qmax: int = 127):
        if not self._reservoir.size:
            return 1e-8
        return max(float(np.percentile(self._reservoir, self.percentile)),
                   1e-8) / qmax


class MSEObserver(BaseObserver):
    """Searches the clip range minimizing quantization MSE (parity:
    MSEObserver). Candidate scales are fractions of the observed absmax."""

    def __init__(self, steps: int = 20):
        super().__init__()
        self.steps = steps
        self._amax = 0.0
        self._samples = []

    def observe(self, x):
        arr = np.asarray(x, dtype=np.float32).ravel()
        if arr.size > (1 << 18):
            arr = arr[:: arr.size // (1 << 18) + 1]
        self._samples.append(arr)
        self._amax = max(self._amax, float(np.max(np.abs(arr))))

    def scale(self, qmax: int = 127):
        if not self._samples or self._amax == 0.0:
            return 1e-8
        v = np.concatenate(self._samples)
        best, best_err = self._amax, np.inf
        for i in range(self.steps):
            amax = self._amax * (1.0 - i / (2.0 * self.steps))
            s = amax / qmax
            q = np.clip(np.round(v / s), -qmax, qmax) * s
            err = float(np.mean((v - q) ** 2))
            if err < best_err:
                best, best_err = amax, err
        return max(best, 1e-8) / qmax
