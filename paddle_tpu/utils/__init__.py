"""paddle_tpu.utils (parity: paddle.utils — dlpack interop; the
cpp_extension/install-check machinery is N/A in this build)."""

from . import dlpack  # noqa: F401

__all__ = ["dlpack"]
