"""paddle_tpu.utils (parity: paddle.utils — dlpack interop; the
cpp_extension/install-check machinery is N/A in this build)."""

import contextlib as _contextlib

from . import dlpack  # noqa: F401

__all__ = ["dlpack", "deprecated", "try_import", "run_check", "unique_name"]


def deprecated(update_to="", since="", reason="", level=1):
    """Parity: paddle.utils.deprecated — the reference's documented
    level semantics: 0 = suppress the message, 1 = warn (default),
    2 = raise RuntimeError."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            msg = f"{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level >= 2:
                raise RuntimeError(msg)
            if level == 1:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return inner

    return wrap


def try_import(module_name, err_msg=None):
    """Parity: paddle.utils.try_import."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed"
        ) from e


def run_check():
    """Parity: paddle.utils.run_check — one tiny compiled computation
    on the available devices, reporting what the install can do."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    out = jax.jit(lambda x: (x @ x).sum())(jnp.eye(8))
    assert float(out) == 8.0
    print(f"paddle_tpu is installed and working on {len(devs)} "
          f"{devs[0].platform} device(s): {devs[0].device_kind}")


class _UniqueName:
    """Parity: paddle.utils.unique_name (generate/guard/switch)."""

    def __init__(self):
        self._counters = {}

    def generate(self, key):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    def switch(self, new_generator=None):
        old = dict(self._counters)
        self._counters = {} if new_generator is None else new_generator
        return old

    @_contextlib.contextmanager
    def guard(self, new_generator=None):
        old = self.switch({} if new_generator is None else new_generator)
        try:
            yield
        finally:
            self._counters = old


unique_name = _UniqueName()
