"""DLPack interop (parity: paddle.utils.dlpack — zero-copy exchange with
torch/numpy/cupy via the standard __dlpack__ protocol)."""

from __future__ import annotations

import jax.numpy as jnp


def to_dlpack(x):
    """Export a framework tensor as a DLPack capsule."""
    from ..core.parameter import Parameter

    if isinstance(x, Parameter):
        x = x.value
    return x.__dlpack__()


def from_dlpack(capsule_or_tensor):
    """Import from a DLPack capsule OR any object implementing
    ``__dlpack__`` (torch/cupy/numpy arrays directly)."""
    return jnp.from_dlpack(capsule_or_tensor)
