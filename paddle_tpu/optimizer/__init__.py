"""paddle_tpu.optimizer (parity: paddle.optimizer)."""

from . import lr  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from .optimizer import (  # noqa: F401
    SGD,
    Adagrad,
    Adam,
    AdamW,
    Lamb,
    Momentum,
    Optimizer,
)

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Lamb",
    "lr", "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
]
