"""paddle_tpu.optimizer (parity: paddle.optimizer)."""

from . import lr  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from .lbfgs import LBFGS  # noqa: F401
from .optimizer import (  # noqa: F401
    ASGD,
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Lars,
    Momentum,
    NAdam,
    Optimizer,
    RAdam,
    RMSProp,
    Rprop,
)

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Lamb",
    "Lars", "RMSProp", "Adamax", "Adadelta", "NAdam", "RAdam", "ASGD", "Rprop",
    "LBFGS",
    "lr", "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
]
