"""L-BFGS (parity: paddle.optimizer.LBFGS, python/paddle/optimizer/lbfgs.py
— itself the torch-style closure API: ``opt.step(closure)`` re-evaluates
the loss, with history_size curvature pairs and an optional strong-Wolfe
line search).

TPU design note: L-BFGS is a host-driven outer loop by nature (data-
dependent convergence tests, variable-length line search), so unlike the
first-order optimizers it is NOT a jittable pytree update. The inner
pieces — closure evaluation and the two-loop recursion — run on device;
the control flow stays in Python, which matches how the reference drives
it from the Python layer.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ..core.parameter import Parameter


def _cubic_interpolate(x1, f1, g1, x2, f2, g2):
    """Minimizer of the cubic through (x1, f1, g1), (x2, f2, g2)
    (torch/paddle ``_cubic_interpolate``); bisection when the cubic has
    no real minimum in between."""
    import math

    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1 * d1 - g1 * g2
    xmin, xmax = min(x1, x2), max(x1, x2)
    if d2_square >= 0:
        d2 = math.sqrt(d2_square)
        if x1 <= x2:
            denom = g2 - g1 + 2 * d2
            if denom != 0:
                t = x2 - (x2 - x1) * ((g2 + d2 - d1) / denom)
                return min(max(t, xmin), xmax)
        else:
            denom = g1 - g2 + 2 * d2
            if denom != 0:
                t = x1 - (x1 - x2) * ((g1 + d2 - d1) / denom)
                return min(max(t, xmin), xmax)
    return (xmin + xmax) / 2.0


def _flatten(tensors):
    return jnp.concatenate([jnp.ravel(t.astype(jnp.float32)) for t in tensors])


class LBFGS:
    def __init__(
        self,
        learning_rate: float = 1.0,
        max_iter: int = 20,
        max_eval: Optional[int] = None,
        tolerance_grad: float = 1e-7,
        tolerance_change: float = 1e-9,
        history_size: int = 100,
        line_search_fn: Optional[str] = None,
        parameters=None,
        weight_decay=None,
        grad_clip=None,
        name=None,
    ):
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.lr = float(learning_rate)
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._parameter_list: List[Parameter] = (
            list(parameters) if parameters is not None else []
        )
        # persistent state across step() calls (torch/paddle parity)
        self._state = {
            "func_evals": 0, "n_iter": 0,
            "old_sks": [], "old_yks": [], "ro": [],
            "d": None, "t": None, "prev_flat_grad": None, "H_diag": 1.0,
        }

    # -- parameter plumbing -------------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list if p.trainable]

    def _gather(self):
        return [jnp.asarray(p.value) for p in self._params()]

    def _scatter(self, flat):
        i = 0
        for p in self._params():
            n = int(jnp.size(p.value))
            chunk = flat[i:i + n].reshape(p.value.shape).astype(p.value.dtype)
            p.value = chunk
            i += n

    def _eval(self, closure, flat_x):
        """Set params to flat_x, run closure, return (loss, flat_grad)."""
        self._scatter(flat_x)
        loss = closure()
        grads = [jnp.asarray(p.grad) if p.grad is not None
                 else jnp.zeros_like(jnp.asarray(p.value))
                 for p in self._params()]
        self._state["func_evals"] += 1
        return float(loss), _flatten(grads)

    # -- strong Wolfe (cubic-interpolation zoom, torch _strong_wolfe) -------
    def _strong_wolfe(self, closure, x, t, d, f, g, gtd,
                      c1=1e-4, c2=0.9, max_ls=25):
        d_norm = float(jnp.max(jnp.abs(d)))
        g_prev, f_prev, t_prev = g, f, 0.0
        ls_iter = 0
        # bracket phase
        f_new, g_new = self._eval(closure, x + t * d)
        gtd_new = float(g_new @ d)
        bracket = None
        while ls_iter < max_ls:
            if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
                bracket = (t_prev, t, f_prev, f_new, g_prev, g_new)
                break
            if abs(gtd_new) <= -c2 * gtd:
                return f_new, g_new, t, ls_iter
            if gtd_new >= 0:
                bracket = (t_prev, t, f_prev, f_new, g_prev, g_new)
                break
            t_prev, f_prev, g_prev = t, f_new, g_new
            t = 2.0 * t  # bracket expansion
            f_new, g_new = self._eval(closure, x + t * d)
            gtd_new = float(g_new @ d)
            ls_iter += 1
        if bracket is None:
            return f_new, g_new, t, ls_iter
        lo_t, hi_t, lo_f, hi_f, lo_g, hi_g = bracket
        if lo_f > hi_f:
            lo_t, hi_t, lo_f, hi_f, lo_g, hi_g = \
                hi_t, lo_t, hi_f, lo_f, hi_g, lo_g
        lo_gtd, hi_gtd = float(lo_g @ d), float(hi_g @ d)
        # zoom phase: cubic interpolation with the torch/paddle
        # insufficient-progress safeguard (falls back toward the bounds,
        # then bisection) — matches _strong_wolfe closure-eval counts
        insuf_progress = False
        while ls_iter < max_ls:
            if abs(hi_t - lo_t) * d_norm < self.tolerance_change:
                break
            xmin, xmax = min(lo_t, hi_t), max(lo_t, hi_t)
            t = _cubic_interpolate(lo_t, lo_f, lo_gtd,
                                   hi_t, hi_f, hi_gtd)
            eps = 0.1 * (xmax - xmin)
            if min(xmax - t, t - xmin) < eps:
                if insuf_progress or t >= xmax or t <= xmin:
                    t = xmax - eps if abs(t - xmax) < abs(t - xmin) \
                        else xmin + eps
                    insuf_progress = False
                else:
                    insuf_progress = True
            else:
                insuf_progress = False
            f_new, g_new = self._eval(closure, x + t * d)
            gtd_new = float(g_new @ d)
            ls_iter += 1
            if f_new > (f + c1 * t * gtd) or f_new >= lo_f:
                hi_t, hi_f, hi_g, hi_gtd = t, f_new, g_new, gtd_new
            else:
                if abs(gtd_new) <= -c2 * gtd:
                    return f_new, g_new, t, ls_iter
                if gtd_new * (hi_t - lo_t) >= 0:
                    hi_t, hi_f, hi_g, hi_gtd = lo_t, lo_f, lo_g, lo_gtd
                lo_t, lo_f, lo_g, lo_gtd = t, f_new, g_new, gtd_new
        return lo_f, lo_g, lo_t, ls_iter

    # -- main ---------------------------------------------------------------
    def step(self, closure: Callable[[], jax.Array]):
        """One L-BFGS optimization step (up to max_iter inner iterations).
        ``closure`` must recompute the loss AND refresh ``p.grad`` on every
        call (use paddle_tpu.autograd.backward or set grads manually)."""
        st = self._state
        x0 = _flatten(self._gather())
        loss, flat_grad = self._eval(closure, x0)
        if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
            return jnp.asarray(loss)

        x = x0
        n_inner = 0
        while n_inner < self.max_iter:
            n_inner += 1
            st["n_iter"] += 1
            # direction via two-loop recursion
            if st["prev_flat_grad"] is None:
                d = -flat_grad
                st["H_diag"] = 1.0
            else:
                y = flat_grad - st["prev_flat_grad"]
                s = st["d"] * st["t"]
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(st["old_sks"]) >= self.history_size:
                        st["old_sks"].pop(0)
                        st["old_yks"].pop(0)
                        st["ro"].pop(0)
                    st["old_sks"].append(s)
                    st["old_yks"].append(y)
                    st["ro"].append(1.0 / ys)
                    st["H_diag"] = ys / float(y @ y)
                q = -flat_grad
                alphas = []
                for s_i, y_i, ro_i in zip(reversed(st["old_sks"]),
                                          reversed(st["old_yks"]),
                                          reversed(st["ro"])):
                    alpha = ro_i * float(s_i @ q)
                    alphas.append(alpha)
                    q = q - alpha * y_i
                d = q * st["H_diag"]
                for (s_i, y_i, ro_i), alpha in zip(
                        zip(st["old_sks"], st["old_yks"], st["ro"]),
                        reversed(alphas)):
                    beta = ro_i * float(y_i @ d)
                    d = d + s_i * (alpha - beta)
            st["prev_flat_grad"] = flat_grad

            gtd = float(flat_grad @ d)
            if gtd > -self.tolerance_change:
                break
            t = (min(1.0, 1.0 / float(jnp.sum(jnp.abs(flat_grad)))) * self.lr
                 if st["n_iter"] == 1 else self.lr)

            if self.line_search_fn == "strong_wolfe":
                loss, flat_grad, t, _ = self._strong_wolfe(
                    closure, x, t, d, loss, flat_grad, gtd)
                x = x + t * d
                self._scatter(x)
            else:
                x = x + t * d
                loss, flat_grad = self._eval(closure, x)
            st["d"], st["t"] = d, t

            if st["func_evals"] >= self.max_eval:
                break
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tolerance_grad:
                break
            if float(jnp.max(jnp.abs(t * d))) <= self.tolerance_change:
                break
        return jnp.asarray(loss)

    # paddle Optimizer surface used by schedulers/trainers ------------------
    def get_lr(self):
        return self.lr

    def clear_grad(self):
        for p in self._params():
            p.grad = None

    def state_dict(self):
        st = dict(self._state)
        # snapshot the mutable curvature history — the live lists keep
        # being appended/popped by step()
        for k in ("old_sks", "old_yks", "ro"):
            st[k] = list(st[k])
        return {"lr": self.lr, "state": st}

    def set_state_dict(self, d):
        self.lr = d.get("lr", self.lr)
        self._state.update(d.get("state", {}))
