"""Optimizers: functional core + Paddle-parity stateful wrapper.

Parity: python/paddle/optimizer/ (SGD/Momentum/Adam/AdamW with
``multi_precision`` master weights, grad_clip, weight decay,
apply_decay_param_fun) and the fused multi-tensor kernels
(phi fused_adamw / multi_tensor_adam) — on TPU the "fusion" is XLA's: the
whole-pytree update is one compiled program, so per-tensor kernel-launch
overhead (the thing multi-tensor kernels exist to kill) does not exist.

Design: an optimizer owns no tensors. ``init(params)`` returns a state
pytree; ``update(grads, state, params)`` returns (new_params, new_state).
Both run under jit with params/grads/state sharded by the ZeRO engine —
optimizer-state sharding (stage 1/2) falls out of giving state the same
PartitionSpec as its parameter. The stateful ``.step()`` path mutates
Parameter cells eagerly for small-scale/naive use.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.parameter import Parameter
from .clip import ClipGradBase
from .lr import LRScheduler, resolve_lr


def _to_f32(x):
    return x.astype(jnp.float32)


class Optimizer:
    """Base. Subclasses implement ``_init_slot(param)`` and
    ``_apply(update_ctx, name, param_f32, grad_f32, slots)``."""

    def __init__(
        self,
        learning_rate=0.001,
        parameters=None,
        weight_decay: float = 0.0,
        grad_clip: Optional[ClipGradBase] = None,
        multi_precision: bool = True,
        apply_decay_param_fun: Optional[Callable[[str], bool]] = None,
        name: Optional[str] = None,
    ):
        self.base_lr, self.lr_schedule = resolve_lr(learning_rate)
        self._lr_scheduler = (
            learning_rate if isinstance(learning_rate, LRScheduler) else None
        )
        self.weight_decay = float(weight_decay or 0.0)
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        self.apply_decay_param_fun = apply_decay_param_fun
        self._parameter_list = list(parameters) if parameters is not None else None
        self._eager_state = None
        self._accumulated_grads = None

    # ------------------------------------------------------------------
    # functional core
    # ------------------------------------------------------------------
    def init(self, params: Dict[str, jax.Array]):
        state = {
            "step": jnp.zeros((), jnp.int32),
            "slots": {
                name: self._init_slot(p) for name, p in params.items()
            },
        }
        if self.multi_precision:
            state["master"] = {
                name: _to_f32(p)
                for name, p in params.items()
                if p.dtype in (jnp.bfloat16, jnp.float16)
            }
        return state

    def _lr_value(self, step):
        if self.lr_schedule is not None:
            return self.lr_schedule(step)
        return jnp.asarray(self.base_lr, jnp.float32)

    def update(self, grads, state, params, scale=None):
        """One optimizer step. All-jnp; call inside jit.

        ``scale``: optional gradient scale divisor (AMP GradScaler parity —
        on TPU bf16 needs no loss scaling, but the hook exists).
        """
        step = state["step"] + 1
        lr = self._lr_value(step)
        if scale is not None:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / scale, grads
            )
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)

        master = state.get("master", {})
        new_params, new_slots, new_master = {}, {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_slots[name] = state["slots"][name]
                if name in master:
                    new_master[name] = master[name]
                continue
            # fp32 math on the master copy (or the param itself if fp32)
            pf = master.get(name, p).astype(jnp.float32)
            gf = g.astype(jnp.float32)
            decay = self.weight_decay
            if decay and self.apply_decay_param_fun is not None:
                if not self.apply_decay_param_fun(name):
                    decay = 0.0
            pf_new, slots_new = self._apply(
                lr, step, name, pf, gf, state["slots"][name], decay
            )
            new_params[name] = pf_new.astype(p.dtype)
            new_slots[name] = slots_new
            if name in master:
                new_master[name] = pf_new
        new_state = {"step": step, "slots": new_slots}
        if self.multi_precision:
            new_state["master"] = new_master
        return new_params, new_state

    # subclass API ------------------------------------------------------
    def _init_slot(self, p):
        raise NotImplementedError

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        raise NotImplementedError

    # ------------------------------------------------------------------
    # eager paddle-style API
    # ------------------------------------------------------------------
    def _eager_params(self) -> Dict[str, Parameter]:
        if self._parameter_list is None:
            raise ValueError("optimizer created without parameters=")
        return {p.name: p for p in self._parameter_list if p.trainable}

    def apply_gradients(self, grads: Dict[str, jax.Array]):
        """Eagerly apply a {param_name: grad} dict to the held parameters."""
        objs = self._eager_params()
        params = {n: p.value for n, p in objs.items()}
        if self._eager_state is None:
            self._eager_state = self.init(params)
        new_params, self._eager_state = self.update(
            grads, self._eager_state, params
        )
        for n, p in objs.items():
            p.value = new_params[n]

    def step(self):
        """Apply grads accumulated via ``set_gradients`` (or raise)."""
        if self._accumulated_grads is None:
            raise RuntimeError(
                "no gradients: call opt.set_gradients(grads) first (grads "
                "come from paddle_tpu.autograd.backward)"
            )
        self.apply_gradients(self._accumulated_grads)
        self._accumulated_grads = None

    def set_gradients(self, grads: Dict[str, jax.Array]):
        self._accumulated_grads = grads

    def clear_grad(self):
        self._accumulated_grads = None

    def get_lr(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler.get_lr()
        return self.base_lr

    def set_lr(self, lr: float):
        self.base_lr = float(lr)
        self.lr_schedule = None

    def state_dict(self):
        out = {"base_lr": self.base_lr}
        if self._eager_state is not None:
            out["state"] = self._eager_state
        if self._lr_scheduler is not None:
            out["lr_scheduler"] = self._lr_scheduler.state_dict()
        return out

    def set_state_dict(self, d):
        self.base_lr = d.get("base_lr", self.base_lr)
        if "state" in d:
            self._eager_state = d["state"]
        if "lr_scheduler" in d and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(d["lr_scheduler"])


class SGD(Optimizer):
    def _init_slot(self, p):
        return {}

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        return pf - lr * gf, {}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=0.0, grad_clip=None,
                 multi_precision=True, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slot(self, p):
        return {"velocity": jnp.zeros(p.shape, jnp.float32)}

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        v = self.momentum * slots["velocity"] + gf
        if self.use_nesterov:
            upd = gf + self.momentum * v
        else:
            upd = v
        return pf - lr * upd, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.0,
                 grad_clip=None, multi_precision=True, lazy_mode=False,
                 moment_dtype=None, **kw):
        """``moment_dtype``: storage dtype for the m/v slots (default
        fp32, the reference's fused_adamw layout). ``bfloat16`` is the
        TPU bandwidth option: the update step is pure HBM traffic (the
        876M headline measured it at roofline, 10% of step time), and
        halving moment bytes cuts that traffic ~29% and residency by
        4 bytes/param. Math still runs in fp32 — only storage rounds;
        bf16 keeps fp32's exponent range so v never under/overflows,
        and the ~0.4% mantissa rounding on the EMAs is noise relative
        to grad stochasticity (see test_optimizer bf16-moment
        convergence parity)."""
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.moment_dtype = (jnp.dtype(moment_dtype) if moment_dtype
                             else jnp.float32)

    def _init_slot(self, p):
        return {
            "moment1": jnp.zeros(p.shape, self.moment_dtype),
            "moment2": jnp.zeros(p.shape, self.moment_dtype),
        }

    def _decoupled(self):
        return False

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay and not self._decoupled():
            gf = gf + decay * pf  # L2-style (Adam)
        m = self.beta1 * slots["moment1"].astype(jnp.float32) \
            + (1 - self.beta1) * gf
        v = self.beta2 * slots["moment2"].astype(jnp.float32) \
            + (1 - self.beta2) * jnp.square(gf)
        stepf = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(self.beta1, stepf))
        vhat = v / (1 - jnp.power(self.beta2, stepf))
        upd = mhat / (jnp.sqrt(vhat) + self.epsilon)
        if decay and self._decoupled():
            upd = upd + decay * pf  # decoupled (AdamW)
        dt = self.moment_dtype
        return pf - lr * upd, {"moment1": m.astype(dt),
                               "moment2": v.astype(dt)}


class AdamW(Adam):
    """Decoupled weight decay (parity: paddle.optimizer.AdamW; phi
    fused_adamw kernel semantics: decay applied decoupled, master weights
    when multi_precision)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 grad_clip=None, multi_precision=True,
                 apply_decay_param_fun=None, moment_dtype=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision,
                         apply_decay_param_fun=apply_decay_param_fun,
                         moment_dtype=moment_dtype, **kw)

    def _decoupled(self):
        return True


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def _init_slot(self, p):
        return {
            "moment": jnp.full(p.shape, self.initial_accumulator_value, jnp.float32)
        }

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        acc = slots["moment"] + jnp.square(gf)
        return pf - lr * gf / (jnp.sqrt(acc) + self.epsilon), {"moment": acc}


class Lamb(Optimizer):
    """Parity: paddle.optimizer.Lamb (used by LARS/LAMB meta-optimizers)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, lamb_weight_decay=0.01,
                 grad_clip=None, multi_precision=True,
                 exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, multi_precision, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.exclude_from_weight_decay_fn = exclude_from_weight_decay_fn

    def _init_slot(self, p):
        return {
            "moment1": jnp.zeros(p.shape, jnp.float32),
            "moment2": jnp.zeros(p.shape, jnp.float32),
        }

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if self.exclude_from_weight_decay_fn is not None and \
                self.exclude_from_weight_decay_fn(name):
            decay = 0.0
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * gf
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(gf)
        stepf = step.astype(jnp.float32)
        mhat = m / (1 - jnp.power(self.beta1, stepf))
        vhat = v / (1 - jnp.power(self.beta2, stepf))
        r = mhat / (jnp.sqrt(vhat) + self.epsilon) + decay * pf
        w_norm = jnp.linalg.norm(pf)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where(
            (w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0
        )
        return pf - lr * trust * r, {"moment1": m, "moment2": v}


class Lars(Optimizer):
    """Layer-wise Adaptive Rate Scaling momentum (parity: the reference's
    lars_momentum kernel + fleet LARS meta-optimizer,
    fleet/meta_optimizers/lars_optimizer.py): per-parameter trust ratio
    local_lr = lr * coeff * ||w|| / (||g|| + decay*||w|| + eps), then
    classic momentum on (g + decay*w). On TPU the whole-pytree update is
    one XLA program — the norms are fused reductions, no multi-tensor
    kernel needed."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 lars_coeff=0.001, lars_weight_decay=0.0005, epsilon=0.0,
                 exclude_from_weight_decay=None, grad_clip=None,
                 multi_precision=True, **kw):
        super().__init__(learning_rate, parameters, lars_weight_decay,
                         grad_clip, multi_precision, **kw)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.epsilon = epsilon
        self.exclude_from_weight_decay = list(exclude_from_weight_decay or [])

    def _init_slot(self, p):
        return {"velocity": jnp.zeros(p.shape, jnp.float32)}

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if any(tok in name for tok in self.exclude_from_weight_decay):
            decay = 0.0
        w_norm = jnp.linalg.norm(pf)
        g_norm = jnp.linalg.norm(gf)
        denom = g_norm + decay * w_norm + self.epsilon
        # trust-ratio branch gates on g_norm like the reference kernel:
        # on an all-zero grad the update falls back to plain lr
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self.lars_coeff * w_norm / jnp.maximum(denom, 1e-20),
            lr,
        )
        v = self.momentum * slots["velocity"] + local_lr * (gf + decay * pf)
        return pf - v, {"velocity": v}


class RMSProp(Optimizer):
    """Parity: paddle.optimizer.RMSProp (rho/epsilon/momentum/centered —
    phi rmsprop_kernel semantics)."""

    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True,
                 **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def _init_slot(self, p):
        s = {
            "mean_square": jnp.zeros(p.shape, jnp.float32),
            "momentum": jnp.zeros(p.shape, jnp.float32),
        }
        if self.centered:
            s["mean_grad"] = jnp.zeros(p.shape, jnp.float32)
        return s

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        ms = self.rho * slots["mean_square"] + (1 - self.rho) * jnp.square(gf)
        out = {"mean_square": ms}
        if self.centered:
            mg = self.rho * slots["mean_grad"] + (1 - self.rho) * gf
            denom = jnp.sqrt(ms - jnp.square(mg) + self.epsilon)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self.epsilon)
        mom = self.momentum * slots["momentum"] + lr * gf / denom
        out["momentum"] = mom
        return pf - mom, out


class Adamax(Optimizer):
    """Parity: paddle.optimizer.Adamax (infinity-norm Adam variant)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.0,
                 grad_clip=None, multi_precision=True, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slot(self, p):
        return {
            "moment": jnp.zeros(p.shape, jnp.float32),
            "inf_norm": jnp.zeros(p.shape, jnp.float32),
        }

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        m = self.beta1 * slots["moment"] + (1 - self.beta1) * gf
        u = jnp.maximum(self.beta2 * slots["inf_norm"], jnp.abs(gf))
        stepf = step.astype(jnp.float32)
        lr_t = lr / (1 - jnp.power(self.beta1, stepf))
        return (pf - lr_t * m / (u + self.epsilon),
                {"moment": m, "inf_norm": u})


class Adadelta(Optimizer):
    """Parity: paddle.optimizer.Adadelta (accumulated grad/update RMS)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=0.0, grad_clip=None,
                 multi_precision=True, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.epsilon, self.rho = epsilon, rho

    def _init_slot(self, p):
        return {
            "avg_squared_grad": jnp.zeros(p.shape, jnp.float32),
            "avg_squared_update": jnp.zeros(p.shape, jnp.float32),
        }

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        g2 = self.rho * slots["avg_squared_grad"] \
            + (1 - self.rho) * jnp.square(gf)
        upd = gf * jnp.sqrt(
            (slots["avg_squared_update"] + self.epsilon)
            / (g2 + self.epsilon)
        )
        u2 = self.rho * slots["avg_squared_update"] \
            + (1 - self.rho) * jnp.square(upd)
        return pf - lr * upd, {
            "avg_squared_grad": g2, "avg_squared_update": u2,
        }


class NAdam(Optimizer):
    """Parity: paddle.optimizer.NAdam (Nesterov-momentum Adam with the
    momentum_decay schedule mu_t = beta1*(1 - 0.5*0.96^(t*psi)))."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True,
                 **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.momentum_decay = momentum_decay

    def _init_slot(self, p):
        return {
            "moment1": jnp.zeros(p.shape, jnp.float32),
            "moment2": jnp.zeros(p.shape, jnp.float32),
        }

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        stepf = step.astype(jnp.float32)
        psi = self.momentum_decay
        mu_t = self.beta1 * (1 - 0.5 * jnp.power(0.96, stepf * psi))
        mu_t1 = self.beta1 * (1 - 0.5 * jnp.power(0.96, (stepf + 1) * psi))
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * gf
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(gf)
        mu_prod = slots.get("mu_prod", jnp.ones((), jnp.float32)) * mu_t
        mhat = (mu_t1 * m / (1 - mu_prod * mu_t1)
                + (1 - mu_t) * gf / (1 - mu_prod))
        vhat = v / (1 - jnp.power(self.beta2, stepf))
        new = pf - lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return new, {"moment1": m, "moment2": v, "mu_prod": mu_prod}

    def init(self, params):
        state = super().init(params)
        for name in state["slots"]:
            state["slots"][name]["mu_prod"] = jnp.ones((), jnp.float32)
        return state


class RAdam(Optimizer):
    """Parity: paddle.optimizer.RAdam (rectified Adam: SGD-with-momentum
    warmup until the variance-rectification term rho_t > 5)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.0,
                 grad_clip=None, multi_precision=True, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slot(self, p):
        return {
            "moment1": jnp.zeros(p.shape, jnp.float32),
            "moment2": jnp.zeros(p.shape, jnp.float32),
        }

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * gf
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(gf)
        stepf = step.astype(jnp.float32)
        beta2_t = jnp.power(self.beta2, stepf)
        rho_inf = 2.0 / (1.0 - self.beta2) - 1.0
        rho_t = rho_inf - 2.0 * stepf * beta2_t / (1.0 - beta2_t)
        mhat = m / (1 - jnp.power(self.beta1, stepf))
        r = jnp.sqrt(
            jnp.maximum(
                (rho_t - 4) * (rho_t - 2) * rho_inf
                / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-8),
                0.0,
            )
        )
        vhat = jnp.sqrt(v / (1 - beta2_t)) + self.epsilon
        adam_step = lr * r * mhat / vhat
        sgd_step = lr * mhat
        new = pf - jnp.where(rho_t > 5.0, adam_step, sgd_step)
        return new, {"moment1": m, "moment2": v}


class ASGD(Optimizer):
    """Parity: paddle.optimizer.ASGD (averaged SGD over a window of the
    last ``n`` gradients; phi asgd_kernel keeps a running sum ``d`` and a
    per-index history ``y``. TPU design: the ring-buffer of n historical
    grads is memory-hostile; we keep the running-mean recurrence
    d_t = d_{t-1} - y_old/n + g/n with an exponential window, which paddle
    itself reduces to when n >= t)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True,
                 **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, **kw)
        self.batch_num = max(1, int(batch_num))

    def _init_slot(self, p):
        return {"d": jnp.zeros(p.shape, jnp.float32)}

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        if decay:
            gf = gf + decay * pf
        n = jnp.minimum(step.astype(jnp.float32), float(self.batch_num))
        d = slots["d"] + (gf - slots["d"]) / n
        return pf - lr * d, {"d": d}


class Rprop(Optimizer):
    """Parity: paddle.optimizer.Rprop (sign-based resilient prop; per-weight
    step sizes grown/shrunk by the grad-sign agreement)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=True, **kw):
        super().__init__(learning_rate, parameters, 0.0, grad_clip,
                         multi_precision, **kw)
        self.lr_min, self.lr_max = learning_rate_range
        self.eta_neg, self.eta_pos = etas

    def _init_slot(self, p):
        return {
            "prev_grad": jnp.zeros(p.shape, jnp.float32),
            "lrs": jnp.full(p.shape, self.base_lr, jnp.float32),
        }

    def _apply(self, lr, step, name, pf, gf, slots, decay):
        sign = jnp.sign(gf * slots["prev_grad"])
        factor = jnp.where(
            sign > 0, self.eta_pos, jnp.where(sign < 0, self.eta_neg, 1.0)
        )
        lrs = jnp.clip(slots["lrs"] * factor, self.lr_min, self.lr_max)
        # on sign flip: zero the grad (skip the update, classic Rprop-)
        g_eff = jnp.where(sign < 0, 0.0, gf)
        new = pf - lrs * jnp.sign(g_eff)
        return new, {"prev_grad": g_eff, "lrs": lrs}
