"""Learning-rate schedulers (parity: python/paddle/optimizer/lr.py).

Each scheduler is both:
  - a Paddle-style stateful object (``.step()``, ``.get_lr()``,
    ``.state_dict()``), and
  - a pure function of the step count (``sched(step) -> lr`` with jnp ops),
    so the jitted train step computes the LR on device with no host sync.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.last_lr = None
        self.step()

    # ---- pure functional form (jittable) ----
    def lr_at(self, step):
        raise NotImplementedError

    def __call__(self, step):
        return self.lr_at(step)

    # ---- stateful paddle API ----
    def step(self, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1
        self.last_lr = float(self.lr_at(jnp.asarray(self.last_epoch)))

    def get_lr(self):
        return self.last_lr

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, d):
        self.last_epoch = d["last_epoch"]
        self.last_lr = d["last_lr"]


class ConstantLR(LRScheduler):
    def lr_at(self, step):
        return jnp.asarray(self.base_lr, jnp.float32)


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        step = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(a, b)


class LinearWarmup(LRScheduler):
    """Warm up from start_lr to end_lr over warmup_steps, then follow the
    wrapped schedule (or stay at end_lr if wrapping a float)."""

    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1):
        self.inner = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = end_lr if isinstance(learning_rate, (int, float)) else learning_rate.base_lr
        super().__init__(base, last_epoch)

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            step / max(self.warmup_steps, 1), 1.0
        )
        if isinstance(self.inner, (int, float)):
            after = jnp.asarray(self.inner, jnp.float32)
        else:
            after = self.inner.lr_at(jnp.maximum(step - self.warmup_steps, 0))
        return jnp.where(step < self.warmup_steps, warm, after)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / self.T_max, 0.0, 1.0)
        return self.eta_min + 0.5 * (self.base_lr - self.eta_min) * (
            1.0 + jnp.cos(jnp.pi * frac)
        )


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        return self.base_lr * jnp.power(
            self.gamma, jnp.asarray(step, jnp.float32)
        )


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        k = jnp.floor(jnp.asarray(step, jnp.float32) / self.step_size)
        return self.base_lr * jnp.power(self.gamma, k)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / self.decay_steps, 0.0, 1.0)
        return (self.base_lr - self.end_lr) * jnp.power(1 - frac, self.power) + self.end_lr


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch)

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        lr = jnp.asarray(self.values[-1], jnp.float32)
        for b, v in zip(reversed(self.boundaries), reversed(self.values[:-1])):
            lr = jnp.where(step < b, v, lr)
        return lr


def resolve_lr(learning_rate):
    """Return (base_lr_float, schedule_fn|None)."""
    if isinstance(learning_rate, LRScheduler):
        return learning_rate.base_lr, learning_rate.lr_at
    return float(learning_rate), None


class MultiStepDecay(LRScheduler):
    """Parity: paddle.optimizer.lr.MultiStepDecay — gamma applied at each
    milestone epoch."""

    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1):
        self.milestones = sorted(int(m) for m in milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        step = jnp.asarray(step)
        n = jnp.sum(jnp.asarray(self.milestones) <= step)
        return self.base_lr * self.gamma ** n


class NaturalExpDecay(LRScheduler):
    """lr = base * e^(-gamma * epoch)."""

    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        return self.base_lr * jnp.exp(
            -self.gamma * jnp.asarray(step, jnp.float32))


class InverseTimeDecay(LRScheduler):
    """lr = base / (1 + gamma * epoch)."""

    def __init__(self, learning_rate, gamma, last_epoch=-1):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        return self.base_lr / (
            1.0 + self.gamma * jnp.asarray(step, jnp.float32))


class LambdaDecay(LRScheduler):
    """lr = base * lr_lambda(epoch). The lambda must be jnp-traceable for
    in-jit use; plain python lambdas work for the stateful API."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class MultiplicativeDecay(LRScheduler):
    """lr = base * Π_{e≤epoch} lr_lambda(e) — stateful-only (the product
    has no closed form for arbitrary lambdas)."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1):
        self.lr_lambda = lr_lambda
        self._factor = 1.0
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        return jnp.asarray(self.base_lr * self._factor, jnp.float32)

    def step(self, epoch=None):
        prev = self.last_epoch
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1
        if self.last_epoch > 0:
            for e in range(max(prev, 0) + 1, self.last_epoch + 1):
                self._factor *= float(self.lr_lambda(e))
        self.last_lr = float(self.lr_at(self.last_epoch))


class OneCycleLR(LRScheduler):
    """Parity: paddle.optimizer.lr.OneCycleLR — warm up to max_learning_rate
    then anneal to max/divide_factor/end-scale (cosine phase shape)."""

    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=None, phase_pct=0.3, last_epoch=-1):
        self.max_lr = float(max_learning_rate)
        self.total_steps = int(total_steps)
        self.initial_lr = self.max_lr / divide_factor
        self.end_lr = (end_learning_rate if end_learning_rate is not None
                       else self.initial_lr / 1e4)
        self.up_steps = max(int(phase_pct * total_steps), 1)
        super().__init__(self.initial_lr, last_epoch)

    def lr_at(self, step):
        step = jnp.clip(jnp.asarray(step, jnp.float32), 0,
                        self.total_steps)
        up = step / self.up_steps
        lr_up = self.initial_lr + (self.max_lr - self.initial_lr) * \
            0.5 * (1 - jnp.cos(jnp.pi * jnp.clip(up, 0, 1)))
        down = (step - self.up_steps) / max(
            self.total_steps - self.up_steps, 1)
        lr_down = self.end_lr + (self.max_lr - self.end_lr) * \
            0.5 * (1 + jnp.cos(jnp.pi * jnp.clip(down, 0, 1)))
        return jnp.where(step < self.up_steps, lr_up, lr_down)


class CyclicLR(LRScheduler):
    """Parity: paddle.optimizer.lr.CyclicLR (triangular mode)."""

    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up, step_size_down=None, last_epoch=-1):
        self.max_lr = float(max_learning_rate)
        self.up = int(step_size_up)
        self.down = int(step_size_down or step_size_up)
        super().__init__(base_learning_rate, last_epoch)

    def lr_at(self, step):
        cycle_len = self.up + self.down
        pos = jnp.mod(jnp.asarray(step, jnp.float32), cycle_len)
        frac = jnp.where(pos < self.up, pos / self.up,
                         1.0 - (pos - self.up) / self.down)
        return self.base_lr + (self.max_lr - self.base_lr) * frac


class ReduceOnPlateau(LRScheduler):
    """Parity: paddle.optimizer.lr.ReduceOnPlateau — metric-driven decay
    (stateful-only by nature; call ``step(metrics=loss)``). Matches the
    reference's semantics: relative threshold by default
    (threshold_mode="rel") and a cooldown that ticks down every epoch
    while active, suppressing bad-epoch counting."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0,
                 min_lr=0.0, last_epoch=-1):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._lr = float(learning_rate)
        self._best = None
        self._bad = 0
        self._cool = 0
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        return jnp.asarray(self._lr, jnp.float32)

    def _is_better(self, metric):
        if self._best is None:
            return True
        if self.threshold_mode == "rel":
            # reference semantics (paddle/torch ReduceOnPlateau): the
            # dynamic threshold scales best by (1 -/+ threshold) — NOT an
            # abs() margin, which would flip direction for negative
            # metrics (log-likelihoods)
            if self.mode == "min":
                return metric < self._best * (1.0 - self.threshold)
            return metric > self._best * (1.0 + self.threshold)
        if self.mode == "min":
            return metric < self._best - self.threshold
        return metric > self._best + self.threshold

    def step(self, metrics=None, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1
        if metrics is not None:
            m = float(metrics)
            if self._is_better(m):
                self._best = m
                self._bad = 0
            else:
                self._bad += 1
            if self._cool > 0:
                # cooldown ticks EVERY epoch and suppresses bad counting
                self._cool -= 1
                self._bad = 0
            elif self._bad > self.patience:
                self._lr = max(self._lr * self.factor, self.min_lr)
                self._bad = 0
                self._cool = self.cooldown
        self.last_lr = float(self._lr)


class CosineAnnealingWarmRestarts(LRScheduler):
    """Parity: paddle.optimizer.lr.CosineAnnealingWarmRestarts (SGDR):
    cosine anneal over a period of T_0 steps, then restart with the
    period scaled by T_mult."""

    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0.0,
                 last_epoch=-1):
        if T_0 <= 0 or T_mult < 1:
            raise ValueError("T_0 must be > 0 and T_mult >= 1")
        self.T_0 = T_0
        self.T_mult = int(T_mult)
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch)

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.T_mult == 1:
            t_cur = jnp.mod(step, self.T_0)
            t_i = jnp.asarray(self.T_0, jnp.float32)
        else:
            # cycle n starts at T_0*(T_mult^n - 1)/(T_mult - 1)
            m = self.T_mult
            n = jnp.floor(
                jnp.log1p(step * (m - 1) / self.T_0) / jnp.log(float(m)))
            start = self.T_0 * (jnp.power(float(m), n) - 1.0) / (m - 1)
            t_i = self.T_0 * jnp.power(float(m), n)
            t_cur = step - start
        return self.eta_min + 0.5 * (self.base_lr - self.eta_min) * (
            1.0 + jnp.cos(jnp.pi * t_cur / t_i))
