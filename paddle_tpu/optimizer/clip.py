"""Gradient clipping (parity: python/paddle/nn/clip.py —
ClipGradByGlobalNorm / ClipGradByNorm / ClipGradByValue).

Functional: each clip is ``clip(grads_pytree) -> grads_pytree`` and is pure
jnp, so it runs inside the jitted train step. Under GSPMD the global-norm
reduction compiles to the same cross-mesh allreduce the reference performs
explicitly across mp/pp/sharding groups
(HybridParallelClipGrad, fleet/meta_parallel/hybrid_parallel_optimizer.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, grads):
        raise NotImplementedError


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm: float = 1.0):
        self.clip_norm = float(clip_norm)

    def __call__(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return grads
        gnorm_sq = sum(
            jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves
        )
        gnorm = jnp.sqrt(gnorm_sq)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads
        )

    def global_norm(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        return jnp.sqrt(
            sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        )


class ClipGradByNorm(ClipGradBase):
    """Per-tensor norm clip."""

    def __init__(self, clip_norm: float):
        self.clip_norm = float(clip_norm)

    def _one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))
        return (g.astype(jnp.float32) * scale).astype(g.dtype)

    def __call__(self, grads):
        return jax.tree_util.tree_map(self._one, grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads
        )
