"""Probability distributions (parity: python/paddle/distribution/ —
Distribution ABC, Normal, Uniform, Categorical, Bernoulli, kl_divergence).
Sampling draws from the framework RNG (core.random), so it is
deterministic eagerly and key-threaded under jit."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .core import random as random_mod


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        return jnp.exp(self.log_prob(value))


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("normal")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape
        )
        return self.loc + self.scale * jax.random.normal(key, shape)

    rsample = sample

    def log_prob(self, value):
        var = self.scale**2
        return (
            -((value - self.loc) ** 2) / (2 * var)
            - jnp.log(self.scale)
            - 0.5 * math.log(2 * math.pi)
        )

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def kl_divergence(self, other: "Normal"):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("uniform")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.low.shape, self.high.shape
        )
        return jax.random.uniform(
            key, shape, minval=self.low, maxval=self.high
        )

    def log_prob(self, value):
        inside = (value >= self.low) & (value <= self.high)
        return jnp.where(
            inside, -jnp.log(self.high - self.low), -jnp.inf
        )

    def entropy(self):
        return jnp.log(self.high - self.low)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None):
        if logits is None:
            logits = jnp.log(jnp.asarray(probs) + 1e-30)
        self.logits = jnp.asarray(logits, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("categorical")
        return jax.random.categorical(key, self.logits, shape=tuple(shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, jnp.asarray(value)[..., None], axis=-1
        ).squeeze(-1)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs_ = jnp.asarray(probs, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("bernoulli")
        return jax.random.bernoulli(
            key, self.probs_, tuple(shape) + self.probs_.shape
        ).astype(jnp.float32)

    def log_prob(self, value):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p)

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("exponential")
        shape = tuple(shape) + self.rate.shape
        return jax.random.exponential(key, shape) / self.rate

    def log_prob(self, value):
        return jnp.where(value >= 0,
                         jnp.log(self.rate) - self.rate * value, -jnp.inf)

    def entropy(self):
        return 1.0 - jnp.log(self.rate)

    @property
    def mean(self):
        return 1.0 / self.rate


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("laplace")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape)
        return self.loc + self.scale * jax.random.laplace(key, shape)

    def log_prob(self, value):
        return -jnp.abs(value - self.loc) / self.scale - \
            jnp.log(2 * self.scale)

    def entropy(self):
        return 1.0 + jnp.log(2 * self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("gumbel")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape)
        return self.loc + self.scale * jax.random.gumbel(key, shape)

    def log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def entropy(self):
        return jnp.log(self.scale) + 1.0 + float(jnp.euler_gamma)


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = jnp.asarray(concentration, jnp.float32)
        self.rate = jnp.asarray(rate, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("gamma")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.concentration.shape, self.rate.shape)
        return jax.random.gamma(key, self.concentration, shape) / self.rate

    def log_prob(self, value):
        a, b = self.concentration, self.rate
        v = jnp.where(value > 0, value, 1.0)  # avoid nan grads off-support
        lp = (a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
              - jax.scipy.special.gammaln(a))
        return jnp.where(value > 0, lp, -jnp.inf)

    def entropy(self):
        a, b = self.concentration, self.rate
        return (a - jnp.log(b) + jax.scipy.special.gammaln(a)
                + (1 - a) * jax.scipy.special.digamma(a))

    @property
    def mean(self):
        return self.concentration / self.rate


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = jnp.asarray(alpha, jnp.float32)
        self.beta = jnp.asarray(beta, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("beta")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.alpha.shape, self.beta.shape)
        return jax.random.beta(key, self.alpha, self.beta, shape)

    def log_prob(self, value):
        a, b = self.alpha, self.beta
        inside = (value > 0) & (value < 1)
        v = jnp.where(inside, value, 0.5)
        lp = ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
              - _betaln(a, b))
        return jnp.where(inside, lp, -jnp.inf)

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        return (_betaln(a, b) - (a - 1) * dg(a) - (b - 1) * dg(b)
                + (a + b - 2) * dg(a + b))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = jnp.asarray(concentration, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("dirichlet")
        return jax.random.dirichlet(key, self.concentration, tuple(shape))

    def log_prob(self, value):
        a = self.concentration
        return (jnp.sum((a - 1) * jnp.log(value), axis=-1)
                + jax.scipy.special.gammaln(jnp.sum(a, -1))
                - jnp.sum(jax.scipy.special.gammaln(a), -1))

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        dg = jax.scipy.special.digamma
        return (jnp.sum(jax.scipy.special.gammaln(a), -1)
                - jax.scipy.special.gammaln(a0)
                + (a0 - k) * dg(a0) - jnp.sum((a - 1) * dg(a), -1))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.base = Normal(loc, scale)
        self.loc, self.scale = self.base.loc, self.base.scale

    def sample(self, shape=()):
        return jnp.exp(self.base.sample(shape))

    def log_prob(self, value):
        v = jnp.where(value > 0, value, 1.0)
        lp = self.base.log_prob(jnp.log(v)) - jnp.log(v)
        return jnp.where(value > 0, lp, -jnp.inf)

    def entropy(self):
        return self.base.entropy() + self.loc

    @property
    def mean(self):
        return jnp.exp(self.loc + self.scale**2 / 2)


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_ = jnp.asarray(probs, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("multinomial")
        logits = jnp.log(self.probs_ + 1e-30)
        draws = jax.random.categorical(
            key, logits,
            shape=tuple(shape) + (self.total_count,)
            + self.probs_.shape[:-1])
        k = self.probs_.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        return jnp.sum(onehot, axis=len(shape))

    def log_prob(self, value):
        gl = jax.scipy.special.gammaln
        return (gl(jnp.asarray(self.total_count + 1.0))
                - jnp.sum(gl(value + 1.0), -1)
                + jnp.sum(value * jnp.log(self.probs_ + 1e-30), -1))


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = jnp.asarray(rate, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("poisson")
        return jax.random.poisson(
            key, self.rate, tuple(shape) + self.rate.shape
        ).astype(jnp.float32)

    def log_prob(self, value):
        v = jnp.where(value >= 0, value, 0.0)  # avoid nan grads off-support
        lp = (v * jnp.log(self.rate) - self.rate
              - jax.scipy.special.gammaln(v + 1.0))
        return jnp.where(value >= 0, lp, -jnp.inf)

    @property
    def mean(self):
        return self.rate


def _betaln(a, b):
    gl = jax.scipy.special.gammaln
    return gl(a) + gl(b) - gl(a + b)


def kl_divergence(p: Distribution, q: Distribution):
    dg = jax.scipy.special.digamma
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        lp = jax.nn.log_softmax(p.logits, -1)
        lq = jax.nn.log_softmax(q.logits, -1)
        return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
        return pp * (jnp.log(pp) - jnp.log(qq)) + \
            (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        out = jnp.log((q.high - q.low) / (p.high - p.low))
        ok = (q.low <= p.low) & (p.high <= q.high)
        return jnp.where(ok, out, jnp.inf)
    if isinstance(p, Exponential) and isinstance(q, Exponential):
        r = p.rate / q.rate
        return jnp.log(r) + 1.0 / r - 1.0
    if isinstance(p, Gamma) and isinstance(q, Gamma):
        pa, pb, qa, qb = p.concentration, p.rate, q.concentration, q.rate
        gl = jax.scipy.special.gammaln
        return ((pa - qa) * dg(pa) - gl(pa) + gl(qa)
                + qa * (jnp.log(pb) - jnp.log(qb))
                + pa * (qb - pb) / pb)
    if isinstance(p, Beta) and isinstance(q, Beta):
        gl_t = _betaln(q.alpha, q.beta) - _betaln(p.alpha, p.beta)
        return (gl_t + (p.alpha - q.alpha) * dg(p.alpha)
                + (p.beta - q.beta) * dg(p.beta)
                + (q.alpha - p.alpha + q.beta - p.beta)
                * dg(p.alpha + p.beta))
    if isinstance(p, Dirichlet) and isinstance(q, Dirichlet):
        pa, qa = p.concentration, q.concentration
        gl = jax.scipy.special.gammaln
        pa0 = jnp.sum(pa, -1)
        return (gl(pa0) - jnp.sum(gl(pa), -1)
                - gl(jnp.sum(qa, -1)) + jnp.sum(gl(qa), -1)
                + jnp.sum((pa - qa) * (dg(pa) - dg(pa0)[..., None]), -1))
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})"
    )


# ---------------------------------------------------------------------------
# Transforms (parity: paddle.distribution.transform — Transform,
# AffineTransform, ExpTransform, SigmoidTransform, TanhTransform,
# ChainTransform — and TransformedDistribution). Bijectors carry
# forward/inverse and the log|det J| used for change-of-variables.
# ---------------------------------------------------------------------------
class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)),
                                jnp.shape(x))


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def forward(self, x):
        return jnp.tanh(x)

    def inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-6, 1 - 1e-6))

    def forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """Parity: paddle.distribution.TransformedDistribution — base
    distribution pushed through a bijector (or list composing left to
    right)."""

    def __init__(self, base: Distribution, transforms):
        self.base = base
        if isinstance(transforms, (list, tuple)):
            transforms = ChainTransform(transforms)
        self.transform = transforms

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        return (self.base.log_prob(x)
                - self.transform.forward_log_det_jacobian(x))


# ---------------------------------------------------------------------------
# long-tail distributions (parity: python/paddle/distribution/)
# ---------------------------------------------------------------------------
class Geometric(Distribution):
    """Parity: paddle.distribution.Geometric — pmf over the number of
    failures before the first success, support {0, 1, 2, ...}:
    P(X=k) = (1-p)^k p."""

    def __init__(self, probs):
        self.probs_ = jnp.asarray(probs, jnp.float32)

    @property
    def mean(self):
        return (1.0 - self.probs_) / self.probs_

    @property
    def variance(self):
        return (1.0 - self.probs_) / (self.probs_ ** 2)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("geometric")
        shape = tuple(shape) + self.probs_.shape
        u = jax.random.uniform(key, shape, minval=1e-7, maxval=1.0)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_))

    def log_prob(self, value):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return value * jnp.log1p(-p) + jnp.log(p)

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        q = 1.0 - p
        return -(q * jnp.log(q) + p * jnp.log(p)) / p


class Cauchy(Distribution):
    """Parity: paddle.distribution.Cauchy(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("cauchy")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape)
        return self.loc + self.scale * jax.random.cauchy(key, shape)

    rsample = sample

    def log_prob(self, value):
        z = (jnp.asarray(value, jnp.float32) - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1.0 + z * z))

    def entropy(self):
        return jnp.log(4 * math.pi * self.scale)

    def cdf(self, value):
        z = (jnp.asarray(value, jnp.float32) - self.loc) / self.scale
        return jnp.arctan(z) / math.pi + 0.5

    def kl_divergence(self, other: "Cauchy"):
        # closed form (Chyzak & Nielsen 2019)
        num = (self.scale + other.scale) ** 2 + (self.loc - other.loc) ** 2
        return jnp.log(num / (4.0 * self.scale * other.scale))


class StudentT(Distribution):
    """Parity: paddle.distribution.StudentT(df, loc, scale)."""

    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = jnp.asarray(df, jnp.float32)
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("student_t")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape)
        return self.loc + self.scale * jax.random.t(key, self.df, shape)

    rsample = sample

    def log_prob(self, value):
        gl = jax.scipy.special.gammaln
        v = self.df
        z = (jnp.asarray(value, jnp.float32) - self.loc) / self.scale
        return (gl((v + 1) / 2) - gl(v / 2)
                - 0.5 * jnp.log(v * math.pi) - jnp.log(self.scale)
                - (v + 1) / 2 * jnp.log1p(z * z / v))

    def entropy(self):
        dg = jax.scipy.special.digamma
        gl = jax.scipy.special.gammaln
        v = self.df
        return ((v + 1) / 2 * (dg((v + 1) / 2) - dg(v / 2))
                + 0.5 * jnp.log(v) + _betaln(v / 2, jnp.asarray(0.5))
                + jnp.log(self.scale))


class Binomial(Distribution):
    """Parity: paddle.distribution.Binomial(total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = jnp.asarray(total_count, jnp.float32)
        self.probs_ = jnp.asarray(probs, jnp.float32)

    @property
    def mean(self):
        return self.total_count * self.probs_

    @property
    def variance(self):
        return self.total_count * self.probs_ * (1 - self.probs_)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("binomial")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.total_count.shape, self.probs_.shape)
        n = int(jnp.max(self.total_count))
        u = jax.random.uniform(key, (n,) + shape)
        trial = jnp.arange(n).reshape((n,) + (1,) * len(shape))
        live = trial < self.total_count
        return jnp.sum((u < self.probs_) & live, axis=0).astype(
            jnp.float32)

    def log_prob(self, value):
        gl = jax.scipy.special.gammaln
        k = jnp.asarray(value, jnp.float32)
        n = self.total_count
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return (gl(n + 1) - gl(k + 1) - gl(n - k + 1)
                + k * jnp.log(p) + (n - k) * jnp.log1p(-p))


class ContinuousBernoulli(Distribution):
    """Parity: paddle.distribution.ContinuousBernoulli — density
    C(l) l^x (1-l)^(1-x) on [0, 1]."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs_ = jnp.asarray(probs, jnp.float32)
        self._lims = lims

    def _log_C(self):
        l = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        near = (l > self._lims[0]) & (l < self._lims[1])
        safe = jnp.where(near, 0.25, l)
        log_c = jnp.log(
            jnp.abs(2.0 * jnp.arctanh(1.0 - 2.0 * safe))
            / jnp.abs(1.0 - 2.0 * safe))
        # Taylor at l = 1/2: log 2 + (4/3)(l-1/2)^2 + O(eps^4)
        x = l - 0.5
        taylor = math.log(2.0) + 4.0 / 3.0 * x * x
        return jnp.where(near, taylor, log_c)

    def log_prob(self, value):
        l = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        x = jnp.asarray(value, jnp.float32)
        return (self._log_C() + x * jnp.log(l)
                + (1.0 - x) * jnp.log1p(-l))

    def sample(self, shape=()):
        key = random_mod.next_rng_key("cbernoulli")
        shape = tuple(shape) + self.probs_.shape
        u = jax.random.uniform(key, shape, minval=1e-6, maxval=1 - 1e-6)
        l = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        near = (l > self._lims[0]) & (l < self._lims[1])
        safe = jnp.where(near, 0.25, l)
        icdf = (jnp.log1p(u * (2.0 * safe - 1.0) / (1.0 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return jnp.where(near, u, icdf)

    rsample = sample


class Independent(Distribution):
    """Parity: paddle.distribution.Independent — reinterpret the last
    ``reinterpreted_batch_ndims`` batch dims as event dims (log_prob
    sums over them)."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.ndims = int(reinterpreted_batch_ndims)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return jnp.sum(lp, axis=tuple(range(-self.ndims, 0)))

    def entropy(self):
        return jnp.sum(self.base.entropy(),
                       axis=tuple(range(-self.ndims, 0)))


class ExponentialFamily(Distribution):
    """Parity: paddle.distribution.ExponentialFamily — subclasses give
    natural parameters + log-normalizer A(theta); entropy comes from the
    Bregman identity H = A - <theta, grad A> + E[-h(x)] via jax.grad
    (the reference differentiates A with its autograd too)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        theta = [jnp.asarray(t, jnp.float32)
                 for t in self._natural_parameters]
        a_val = self._log_normalizer(*theta)
        grads = jax.grad(
            lambda *ts: jnp.sum(self._log_normalizer(*ts)),
            argnums=tuple(range(len(theta))))(*theta)
        ent = a_val + self._mean_carrier_measure
        for t, g in zip(theta, grads):
            ent = ent - t * g
        return ent


# user-extensible KL registry (parity: paddle.distribution.register_kl)
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


_builtin_kl = kl_divergence


def kl_divergence(p: Distribution, q: Distribution):  # noqa: F811
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    if isinstance(p, Cauchy) and isinstance(q, Cauchy):
        return p.kl_divergence(q)
    if isinstance(p, Independent) and isinstance(q, Independent) \
            and p.ndims == q.ndims:
        kl = kl_divergence(p.base, q.base)
        return jnp.sum(kl, axis=tuple(range(-p.ndims, 0)))
    return _builtin_kl(p, q)
