"""Probability distributions (parity: python/paddle/distribution/ —
Distribution ABC, Normal, Uniform, Categorical, Bernoulli, kl_divergence).
Sampling draws from the framework RNG (core.random), so it is
deterministic eagerly and key-threaded under jit."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .core import random as random_mod


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        return jnp.exp(self.log_prob(value))


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("normal")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape
        )
        return self.loc + self.scale * jax.random.normal(key, shape)

    rsample = sample

    def log_prob(self, value):
        var = self.scale**2
        return (
            -((value - self.loc) ** 2) / (2 * var)
            - jnp.log(self.scale)
            - 0.5 * math.log(2 * math.pi)
        )

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def kl_divergence(self, other: "Normal"):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("uniform")
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.low.shape, self.high.shape
        )
        return jax.random.uniform(
            key, shape, minval=self.low, maxval=self.high
        )

    def log_prob(self, value):
        inside = (value >= self.low) & (value <= self.high)
        return jnp.where(
            inside, -jnp.log(self.high - self.low), -jnp.inf
        )

    def entropy(self):
        return jnp.log(self.high - self.low)


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None):
        if logits is None:
            logits = jnp.log(jnp.asarray(probs) + 1e-30)
        self.logits = jnp.asarray(logits, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("categorical")
        return jax.random.categorical(key, self.logits, shape=tuple(shape) + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, jnp.asarray(value)[..., None], axis=-1
        ).squeeze(-1)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs_ = jnp.asarray(probs, jnp.float32)

    def sample(self, shape=()):
        key = random_mod.next_rng_key("bernoulli")
        return jax.random.bernoulli(
            key, self.probs_, tuple(shape) + self.probs_.shape
        ).astype(jnp.float32)

    def log_prob(self, value):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p)

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


def kl_divergence(p: Distribution, q: Distribution):
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        lp = jax.nn.log_softmax(p.logits, -1)
        lq = jax.nn.log_softmax(q.logits, -1)
        return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})"
    )
