"""Incubate optimizer wrappers (parity: python/paddle/incubate/optimizer/
— LookAhead, ModelAverage; plus the EMA helper paddle ships as
paddle.static ExponentialMovingAverage, exposed here dynamic-graph style
since this framework has a single execution mode).

Design: all three are *functional wrappers* around the inner optimizer's
(init, update) pytree contract, so they compose with TrainStep/jit and
ZeRO sharding exactly like any base optimizer — slow/averaged weights
inherit the parameter's PartitionSpec.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


class LookAhead:
    """k inner steps with the fast optimizer, then slow-weight
    interpolation: slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def init(self, params):
        return {
            "inner": self.inner.init(params),
            "slow": _tmap(lambda p: p.astype(jnp.float32), params),
            "step": jnp.zeros((), jnp.int32),
        }

    def update(self, grads, state, params, scale=None):
        new_params, inner_state = self.inner.update(
            grads, state["inner"], params, scale=scale
        )
        step = state["step"] + 1
        sync = (step % self.k) == 0

        def merge(slow, fast):
            slow_new = slow + self.alpha * (fast.astype(jnp.float32) - slow)
            return jnp.where(sync, slow_new, slow)

        slow = _tmap(merge, state["slow"], new_params)
        fast = _tmap(
            lambda s, f: jnp.where(sync, s.astype(f.dtype), f),
            slow, new_params,
        )
        return fast, {"inner": inner_state, "slow": slow, "step": step}


class ModelAverage:
    """Running average of parameters over recent steps (parity:
    paddle.incubate.ModelAverage's sum_1/sum_2/sum_3 windowed scheme,
    reduced to the numerically-equivalent exponential/cumulative mean:
    the reference's window is [min_average_window, max_average_window]
    steps; we keep the cumulative mean, restarting when the window
    exceeds max_average_window)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 inner_optimizer=None):
        self.inner = inner_optimizer
        self.max_window = int(max_average_window)

    def init(self, params):
        st = {
            "avg": _tmap(lambda p: p.astype(jnp.float32), params),
            "count": jnp.ones((), jnp.float32),
        }
        if self.inner is not None:
            st["inner"] = self.inner.init(params)
        return st

    def update(self, grads, state, params, scale=None):
        if self.inner is None:
            raise ValueError("ModelAverage needs inner_optimizer for "
                             "functional update()")
        new_params, inner_state = self.inner.update(
            grads, state["inner"], params, scale=scale
        )
        restart = state["count"] >= self.max_window
        count = jnp.where(restart, 1.0, state["count"] + 1.0)

        def upd(avg, p):
            pf = p.astype(jnp.float32)
            cum = avg + (pf - avg) / count
            return jnp.where(restart, pf, cum)

        avg = _tmap(upd, state["avg"], new_params)
        return new_params, {
            "avg": avg, "count": count, "inner": inner_state,
        }

    def apply(self, state, params):
        """Return the averaged weights cast to the params' dtypes (the
        reference's ``apply()`` context for eval)."""
        return _tmap(
            lambda a, p: a.astype(p.dtype), state["avg"], params
        )


class EMA:
    """Exponential moving average of parameters (parity:
    paddle.static.ExponentialMovingAverage). Like the reference, the
    constant ``decay`` is used unless ``thres_steps`` is enabled, in
    which case the warmup schedule decay_t = min(decay, (1+t)/(10+t))
    applies — reference semantics where averaging ramps up from step
    0 instead of starting at full decay."""

    def __init__(self, decay=0.999, thres_steps=None, zero_debias=True):
        self.decay = float(decay)
        # non-None → warmup schedule (the reference takes a step
        # Variable; here the internal step counter plays that role)
        self.thres_steps = thres_steps
        self.zero_debias = zero_debias

    def init(self, params):
        return {
            "ema": _tmap(lambda p: jnp.zeros_like(p, jnp.float32)
                         if self.zero_debias
                         else p.astype(jnp.float32), params),
            "step": jnp.zeros((), jnp.int32),
            # running product of the (time-varying) decays — the exact
            # zero-init debias factor is 1 - prod(decay_i)
            "decay_prod": jnp.ones((), jnp.float32),
        }

    def update(self, state, params):
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        if self.thres_steps is not None:
            decay = jnp.minimum(self.decay, (1.0 + t) / (10.0 + t))
        else:
            decay = jnp.asarray(self.decay, jnp.float32)

        def upd(e, p):
            return decay * e + (1.0 - decay) * p.astype(jnp.float32)

        return {
            "ema": _tmap(upd, state["ema"], params),
            "step": step,
            "decay_prod": state["decay_prod"] * decay,
        }

    def apply(self, state, params):
        if self.zero_debias:
            corr = 1.0 - state["decay_prod"]
            return _tmap(
                lambda e, p: (e / jnp.maximum(corr, 1e-12)).astype(p.dtype),
                state["ema"], params,
            )
        return _tmap(lambda e, p: e.astype(p.dtype), state["ema"], params)
