"""paddle_tpu.incubate (parity: python/paddle/incubate/ — the surfaces
PaddleNLP and the fleet examples actually import: fused nn functional
ops, LookAhead/ModelAverage optimizer wrappers, EMA)."""

from . import nn  # noqa: F401
from .optimizer import EMA, LookAhead, ModelAverage  # noqa: F401

__all__ = ["nn", "LookAhead", "ModelAverage", "EMA"]
