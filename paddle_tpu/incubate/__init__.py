"""paddle_tpu.incubate (parity: python/paddle/incubate/ — the surfaces
PaddleNLP and the fleet examples actually import: fused nn functional
ops, LookAhead/ModelAverage optimizer wrappers, EMA)."""

from . import nn  # noqa: F401
from .optimizer import EMA, LookAhead, ModelAverage  # noqa: F401

__all__ = ["nn", "LookAhead", "ModelAverage", "EMA",
           "segment_sum", "segment_mean", "segment_max", "segment_min",
           "graph_send_recv", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "identity_loss"]


def _n_segments(segment_ids, num_segments):
    """Segment count: explicit > static-shape inference. paddle sizes
    the output dynamically (max_id + 1) — legal in an eager op, not in
    a compiled program, so under tracing callers must pass
    ``num_segments`` (the jit-able extension paddle lacks)."""
    if num_segments is not None:
        return int(num_segments)
    import jax.numpy as jnp

    mx = jnp.max(segment_ids)
    try:
        return int(mx) + 1
    except Exception as e:  # traced: no concrete max available
        raise ValueError(
            "segment ops under jit need an explicit num_segments= "
            "(output shapes must be static in a compiled program)"
        ) from e


def segment_sum(data, segment_ids, name=None, num_segments=None):
    """Parity: paddle.incubate.segment_sum (+ a ``num_segments``
    extension so the op works under jit)."""
    import jax

    n = _n_segments(segment_ids, num_segments)
    return jax.ops.segment_sum(data, segment_ids, num_segments=n)


def _segment_reduce(data, segment_ids, kind, num_segments=None):
    import jax
    import jax.numpy as jnp

    n = _n_segments(segment_ids, num_segments)
    if kind == "mean":
        s = jax.ops.segment_sum(data, segment_ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(data), segment_ids,
                                num_segments=n)
        return s / jnp.maximum(c, 1)
    if kind == "max":
        return jax.ops.segment_max(data, segment_ids, num_segments=n)
    return jax.ops.segment_min(data, segment_ids, num_segments=n)


def segment_mean(data, segment_ids, name=None, num_segments=None):
    return _segment_reduce(data, segment_ids, "mean", num_segments)


def segment_max(data, segment_ids, name=None, num_segments=None):
    return _segment_reduce(data, segment_ids, "max", num_segments)


def segment_min(data, segment_ids, name=None, num_segments=None):
    return _segment_reduce(data, segment_ids, "min", num_segments)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Parity: paddle.incubate.graph_send_recv (graph message passing):
    gather x at src_index, segment-reduce onto dst_index."""
    import jax
    import jax.numpy as jnp

    msgs = x[src_index]
    n = _n_segments(dst_index, out_size)
    pool = pool_type.lower()
    if pool == "sum":
        return jax.ops.segment_sum(msgs, dst_index, num_segments=n)
    if pool == "mean":
        s = jax.ops.segment_sum(msgs, dst_index, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(msgs), dst_index,
                                num_segments=n)
        return s / jnp.maximum(c, 1)
    if pool == "max":
        return jax.ops.segment_max(msgs, dst_index, num_segments=n)
    if pool == "min":
        return jax.ops.segment_min(msgs, dst_index, num_segments=n)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def softmax_mask_fuse(x, mask, name=None):
    """Parity: incubate.softmax_mask_fuse (fused_softmax_mask kernel):
    softmax(x + mask) — one XLA fusion on TPU, no custom kernel needed."""
    import jax

    return jax.nn.softmax(x + mask, axis=-1)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Parity: incubate.softmax_mask_fuse_upper_triangle — causal-masked
    softmax over [b, h, sq, sk]."""
    import jax
    import jax.numpy as jnp

    sq, sk = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((sq, sk), bool))
    return jax.nn.softmax(jnp.where(causal, x, -1e30), axis=-1)


def identity_loss(x, reduction="none"):
    """Parity: paddle.incubate.identity_loss — marks a tensor as a loss
    for the static optimizer; functionally a reduction. Paddle's int
    codes: 0=sum, 1=mean, 2=none."""
    import jax.numpy as jnp

    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 0):
        return jnp.sum(x)
    if reduction in ("mean", 1):
        return jnp.mean(x)
    raise ValueError(f"unknown reduction {reduction!r}")
