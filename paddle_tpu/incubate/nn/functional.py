"""Fused-op functional surface (parity: python/paddle/incubate/nn/
functional/ — fused_rms_norm, fused_layer_norm, fused_rotary_position_
embedding, swiglu, fused_multi_head_attention, fused_linear,
fused_bias_act, fused_dropout_add; reference kernels in
paddle/phi/kernels/fusion/).

TPU-native note: "fused" is a calling convention here, not a promise of a
hand-written kernel — XLA fuses these compositions on its own, and the
genuinely hot ones (attention, rope at long seq) dispatch to the Pallas
kernels. The surface exists so PaddleNLP-style model code ports without
rewrites.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...kernels.rope import apply_rope, rope_frequencies
from ...nn import functional as F


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    y = F.rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        y = y + norm_bias
    return y


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, **kw):
    return F.layer_norm(x, weight=norm_weight, bias=norm_bias,
                        epsilon=epsilon)


def swiglu(x, y=None):
    return F.swiglu(x, y)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    w = weight.T if transpose_weight else weight
    return F.linear(x, w, bias)


def fused_linear_cross_entropy(x, weight, labels, bias=None,
                               transpose_weight=False,
                               ignore_index=-100, seq_chunk=256):
    """Vocab-head projection + softmax cross-entropy without ever
    materializing the full ``[..., seq, vocab]`` logits tensor.

    Math-equivalent to ``F.cross_entropy(F.linear(x, w), labels)`` with
    mean reduction over non-ignored tokens — softmax is row-wise, so
    chunking the sequence axis is exact. Logits exist one seq-chunk at a
    time (f32 ``[..., seq_chunk, vocab]``); the chunk body is
    ``jax.checkpoint``'ed so backward recomputes each chunk's logits and
    accumulates the weight cotangent inside the scan. For a causal-LM
    train step the full-logits pair (f32 log-softmax + bf16 matmul
    output) is the single largest activation — 2.2 GB at 6x2047x32k —
    and this drops peak memory to one chunk regardless of sequence
    length, buying batch (and thus MFU) headroom.

    Parity: the reference's fused softmax-with-cross-entropy CUDA path
    (paddle/phi/kernels/fusion/ + ParallelCrossEntropy family); here the
    fusion is a remat'd scan XLA pipelines.

    x: [..., S, H]; labels: [..., S] int; weight [H, V] (paddle linear
    layout; pass transpose_weight=True for a [V, H] tied-embedding
    matrix). seq_chunk: positions per chunk (S is padded to a multiple
    with ignore_index).
    """
    import jax

    w = weight.T if transpose_weight else weight  # [H, V]
    S, H = x.shape[-2], x.shape[-1]
    xb = x.reshape((-1, S, H))
    yb = labels.reshape((-1, S))
    C = int(min(seq_chunk, S))
    pad = (-S) % C
    if pad:
        xb = jnp.concatenate(
            [xb, jnp.zeros((xb.shape[0], pad, H), xb.dtype)], axis=1)
        yb = jnp.concatenate(
            [yb, jnp.full((yb.shape[0], pad), ignore_index, yb.dtype)],
            axis=1)
    n_chunks = (S + pad) // C

    # chunks are dynamic slices taken INSIDE the scan body — stacking
    # them as a scanned input would materialize a transposed copy of the
    # whole hidden tensor (measured as ~20ms/step of bitcast/copy
    # fusions on v5e)
    @jax.checkpoint
    def body(carry, i):
        h = jax.lax.dynamic_slice_in_dim(xb, i * C, C, 1)  # [B, C, H]
        t = jax.lax.dynamic_slice_in_dim(yb, i * C, C, 1)  # [B, C]
        logits = h @ w
        if bias is not None:
            logits = logits + bias
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        valid = t != ignore_index
        tsafe = jnp.where(valid, t, 0)
        nll = -jnp.take_along_axis(logp, tsafe[..., None], axis=-1)[..., 0]
        s, n = carry
        return (s + jnp.sum(jnp.where(valid, nll, 0.0)),
                n + jnp.sum(valid.astype(jnp.int32))), None

    (loss_sum, count), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        jnp.arange(n_chunks))
    return loss_sum / jnp.maximum(count, 1).astype(jnp.float32)


def fused_bias_act(x, bias=None, act_method="gelu"):
    if bias is not None:
        x = x + bias
    act = getattr(F, act_method)
    return act(x)


def fused_dropout_add(x, y, p=0.0, training=True, mode="upscale_in_train",
                      rng_key=None):
    return F.dropout(x, p=p, training=training, mode=mode,
                     rng_key=rng_key) + y


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    max_position=None):
    """Parity: incubate fused_rope. q/k/v: [b, s, h, d]; rotates every
    tensor given. sin/cos may be the paddle-shaped [1, s, 1, d] tables
    (the duplicated-half layout) or the compact [s, d/2] this package's
    rope kernel uses; None builds default 10000-base tables."""
    s, d = q.shape[1], q.shape[-1]
    if sin is None or cos is None:
        max_pos = s
        if position_ids is not None:
            if max_position is not None:
                max_pos = int(max_position)
            else:
                try:  # concrete ids: size the table to cover them
                    max_pos = int(jnp.max(position_ids)) + 1
                except Exception as e:  # tracer (jit/vmap)
                    raise ValueError(
                        "fused_rope under jit with position_ids needs "
                        "max_position= (or precomputed sin/cos): the "
                        "default table cannot be sized from a traced "
                        "value") from e
        cos_t, sin_t = rope_frequencies(d, max(max_pos, s), dtype=q.dtype)
    else:
        # accept [..., L, d] (duplicated-half paddle layout) or
        # [..., L, d/2] (compact); L may exceed the current seq — keep the
        # table's own length, never regroup by seq
        cos_t = jnp.asarray(cos)
        sin_t = jnp.asarray(sin)
        last = cos_t.shape[-1]
        if last not in (d, d // 2):
            raise ValueError(
                f"fused_rope: sin/cos last dim {last} matches neither "
                f"head_dim {d} nor head_dim/2")
        cos_t = cos_t.reshape(-1, last)
        sin_t = sin_t.reshape(-1, last)
        if last == d:  # duplicated-half layout → compact
            cos_t, sin_t = cos_t[:, : d // 2], sin_t[:, : d // 2]
    def de_interleave(t):
        # interleaved (x0,x1),(x2,x3) pairs → split-half layout
        return t.reshape(*t.shape[:-1], d // 2, 2) \
            .swapaxes(-1, -2).reshape(*t.shape[:-1], d)

    def re_interleave(t):
        return t.reshape(*t.shape[:-1], 2, d // 2) \
            .swapaxes(-1, -2).reshape(*t.shape[:-1], d)

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        if not use_neox_rotary_style:
            t = de_interleave(t)
        rot, _ = apply_rope(t, t, cos_t, sin_t, position_ids=position_ids)
        if not use_neox_rotary_style:
            rot = re_interleave(rot)
        outs.append(rot)
    return tuple(outs)


def fused_multi_head_attention(x, qkv_weight, qkv_bias=None,
                               linear_weight=None, linear_bias=None,
                               num_heads=None, causal=False,
                               attn_mask=None, dropout_rate=0.0,
                               training=True):
    """Parity: incubate fused_multi_head_attention (phi fused_attention
    kernel): one qkv GEMM → attention → output GEMM."""
    b, s, h = x.shape
    qkv = x @ qkv_weight
    if qkv_bias is not None:
        qkv = qkv + qkv_bias
    d = h // num_heads
    qkv = qkv.reshape(b, s, 3, num_heads, d)
    out = F.scaled_dot_product_attention(
        qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
        attn_mask=attn_mask, is_causal=causal,
        dropout_p=dropout_rate, training=training,
    ).reshape(b, s, h)
    if linear_weight is not None:
        out = out @ linear_weight
        if linear_bias is not None:
            out = out + linear_bias
    return out
