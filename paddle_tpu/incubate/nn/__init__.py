"""paddle_tpu.incubate.nn (parity: python/paddle/incubate/nn/)."""

from . import functional  # noqa: F401

__all__ = ["functional"]
