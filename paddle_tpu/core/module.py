"""Layer: the module system.

Parity: ``paddle.nn.Layer`` (upstream: python/paddle/nn/layer/layers.py) —
sublayers, named_parameters, buffers, forward pre/post hooks, train/eval
mode, state_dict/set_state_dict, apply, to(dtype).

TPU-native design: Layers are eager containers of ``Parameter`` cells and
plain-python config. They are **not** pytrees; jitted execution goes
through ``core.functional.functional_call`` which temporarily binds a flat
``{qualified_name: array}`` pytree into the layer tree. This keeps the
user-facing API stateful/Paddle-flavored while every hot path remains a
pure function of (params, buffers, inputs) that XLA can compile once.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from . import initializer as init_mod
from . import random as random_mod
from .parameter import Parameter


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None):
        d = object.__setattr__
        d(self, "_parameters", collections.OrderedDict())
        d(self, "_buffers", collections.OrderedDict())
        d(self, "_non_persistable_buffer_names", set())
        d(self, "_sub_layers", collections.OrderedDict())
        d(self, "_forward_pre_hooks", collections.OrderedDict())
        d(self, "_forward_post_hooks", collections.OrderedDict())
        d(self, "_hook_id", 0)
        d(self, "training", True)
        d(self, "_name_scope", name_scope or type(self).__name__.lower())
        d(self, "_dtype", dtype_mod.get_default_dtype())

    # ------------------------------------------------------------------
    # attribute routing
    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self.__dict__.pop(name, None)
            self._sub_layers.pop(name, None)
            self._parameters[name] = value
        elif isinstance(value, Layer):
            self.__dict__.pop(name, None)
            self._parameters.pop(name, None)
            self._sub_layers[name] = value
        elif name in self.__dict__.get("_buffers", ()):
            # assignment to a registered buffer updates the buffer store so
            # state_dict/functional binding keep seeing the live value
            self._buffers[name] = None if value is None else jnp.asarray(value)
        else:
            self._parameters.pop(name, None)
            self._sub_layers.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in (self._parameters, self._buffers, self._sub_layers):
            if name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    def create_parameter(
        self,
        shape,
        dtype=None,
        default_initializer=None,
        is_bias: bool = False,
        spec=None,
        name: Optional[str] = None,
    ) -> Parameter:
        """Create (and eagerly initialize) a Parameter.

        Parity: Layer.create_parameter in upstream layers.py; bias defaults
        to zeros, weights to Xavier-normal.
        """
        dt = dtype_mod.convert_dtype(dtype or self._dtype)
        default = init_mod.Constant(0.0) if is_bias else init_mod.XavierNormal()
        trainable = True
        optimize_attr = None
        from .parameter import ParamAttr

        if isinstance(default_initializer, ParamAttr):
            attr = default_initializer
            default_initializer = attr.initializer
            trainable = attr.trainable
            name = name or attr.name
            if attr.learning_rate != 1.0:
                optimize_attr = {"learning_rate": attr.learning_rate}
        init = init_mod.resolve(default_initializer, default)
        key = random_mod.next_rng_key("params")
        value = init(key, tuple(shape), dt)
        p = Parameter(value, name=name, trainable=trainable, spec=spec,
                      init_fn=init)
        if optimize_attr:
            p.optimize_attr.update(optimize_attr)
        return p

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        if tensor is not None:
            tensor = jnp.asarray(tensor)
        self.__dict__.pop(name, None)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: Parameter) -> Parameter:
        self._parameters[str(name)] = parameter
        return parameter

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def named_sublayers(
        self, prefix: str = "", include_self: bool = False, layers_set=None
    ) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(
                prefix=p, include_self=True, layers_set=layers_set
            )

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, sub in self._sub_layers.items():
            if sub is not None:
                yield sub

    def named_children(self):
        return iter(self._sub_layers.items())

    def named_parameters(
        self, prefix: str = "", include_sublayers: bool = True
    ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(
            prefix=prefix, include_self=True
        ):
            for pname, param in layer._parameters.items():
                if param is None or id(param) in seen:
                    continue
                seen.add(id(param))
                full = f"{layer_name}.{pname}" if layer_name else pname
                if param.name.startswith("param_"):
                    # adopt the qualified name so eager grads (keyed by
                    # traversal name) line up with Parameter.name
                    param.name = full
                yield full, param
            if not include_sublayers:
                break

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(
        self, prefix: str = "", include_sublayers: bool = True
    ) -> Iterator[Tuple[str, jax.Array]]:
        for layer_name, layer in self.named_sublayers(
            prefix=prefix, include_self=True
        ):
            for bname, buf in layer._buffers.items():
                if buf is None:
                    continue
                full = f"{layer_name}.{bname}" if layer_name else bname
                yield full, buf
            if not include_sublayers:
                break

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # ------------------------------------------------------------------
    # hooks
    # ------------------------------------------------------------------
    def register_forward_pre_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ------------------------------------------------------------------
    # mode / functional application
    # ------------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def to(self, dtype=None):
        """Cast all floating parameters/buffers (parity: Layer.to / amp
        decorate's cast)."""
        if dtype is None:
            return self
        dt = dtype_mod.convert_dtype(dtype)
        for _, p in self.named_parameters():
            if dtype_mod.is_floating_dtype(p.value.dtype):
                if isinstance(p.value, jax.ShapeDtypeStruct):
                    # meta-initialized (core.meta): recast the abstract
                    # placeholder; nothing to allocate
                    p.value = jax.ShapeDtypeStruct(p.value.shape, dt)
                else:
                    p.value = p.value.astype(dt)
        for layer in self.sublayers(include_self=True):
            for bname, buf in list(layer._buffers.items()):
                if buf is not None and dtype_mod.is_floating_dtype(buf.dtype):
                    layer._buffers[bname] = buf.astype(dt)
            layer._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype)

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(
        self, include_sublayers: bool = True, structured_name_prefix: str = ""
    ) -> Dict[str, jax.Array]:
        out = collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            out[name] = p.value
        for layer_name, layer in self.named_sublayers(
            prefix=structured_name_prefix, include_self=True
        ):
            for bname, buf in layer._buffers.items():
                if buf is None or bname in layer._non_persistable_buffer_names:
                    continue
                full = f"{layer_name}.{bname}" if layer_name else bname
                out[full] = buf
        return out

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        """Load values by structured name; shapes must match."""
        params = dict(self.named_parameters())
        missing, unexpected = [], []
        buf_owners = {}
        for layer_name, layer in self.named_sublayers(include_self=True):
            for bname in layer._buffers:
                full = f"{layer_name}.{bname}" if layer_name else bname
                buf_owners[full] = (layer, bname)
        for name, value in state_dict.items():
            if name in params:
                p = params[name]
                value = jnp.asarray(value)
                if tuple(value.shape) != tuple(p.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: got {tuple(value.shape)}, "
                        f"expected {tuple(p.shape)}"
                    )
                p.value = value.astype(p.dtype)
            elif name in buf_owners:
                layer, bname = buf_owners[name]
                layer._buffers[bname] = jnp.asarray(value)
            else:
                unexpected.append(name)
        for name in params:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # call
    # ------------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()"
        )

    def __call__(self, *args, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, args)
            if result is not None:
                args = result if isinstance(result, tuple) else (result,)
        out = self.forward(*args, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, args, out)
            if result is not None:
                out = result
        return out

    # ------------------------------------------------------------------
    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = "\n  ".join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        body = ""
        if extra:
            body += extra
        if lines:
            if extra:
                body += "\n  "
            body += "\n  ".join(lines)
        if body:
            return f"{type(self).__name__}(\n  {body}\n)"
        return f"{type(self).__name__}()"
