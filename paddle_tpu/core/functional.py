"""Functional bridge: run a stateful Layer tree as a pure function.

This is the architectural pivot away from the reference: paddle executes
ops eagerly through a C++ dispatcher (pybind → *_ad_func → phi kernel,
upstream paddle/fluid/eager/), while on TPU the entire train/eval step must
be one XLA program. ``functional_call(layer, params, *args)`` temporarily
binds a flat ``{qualified_name: array}`` dict into the layer tree and calls
``layer(*args)`` — under ``jax.jit`` the bound values are tracers, so the
trace captures a pure function of the parameter pytree while user code
keeps its stateful Paddle-style ``self.weight`` reads.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional

import jax

from . import random as random_mod
from .module import Layer


def extract_params(layer: Layer, trainable_only: bool = False) -> Dict[str, jax.Array]:
    """Flat pytree of parameter values keyed by qualified name."""
    return {
        name: p.value
        for name, p in layer.named_parameters()
        if (p.trainable or not trainable_only)
    }


def extract_param_objs(layer: Layer, trainable_only: bool = False):
    return {
        name: p
        for name, p in layer.named_parameters()
        if (p.trainable or not trainable_only)
    }


def extract_buffers(layer: Layer) -> Dict[str, jax.Array]:
    return dict(layer.named_buffers())


@contextlib.contextmanager
def bind_params(layer: Layer, params: Dict[str, Any], buffers=None):
    """Temporarily swap parameter (and buffer) values in the layer tree."""
    objs = dict(layer.named_parameters())
    saved = {}
    for name, value in params.items():
        p = objs.get(name)
        if p is None:
            raise KeyError(f"unknown parameter {name!r}")
        saved[name] = p.value
        p.value = value
    saved_bufs = []
    if buffers:
        owners = {}
        for layer_name, sub in layer.named_sublayers(include_self=True):
            for bname in sub._buffers:
                full = f"{layer_name}.{bname}" if layer_name else bname
                owners[full] = (sub, bname)
        for name, value in buffers.items():
            if name in owners:
                sub, bname = owners[name]
                saved_bufs.append((sub, bname, sub._buffers[bname]))
                sub._buffers[bname] = value
    try:
        yield
    finally:
        for name, value in saved.items():
            objs[name].value = value
        for sub, bname, value in saved_bufs:
            sub._buffers[bname] = value


def functional_call(
    layer: Layer,
    params: Dict[str, Any],
    *args,
    rngs=None,
    buffers=None,
    **kwargs,
):
    """Pure-functional forward: ``out = f(params, inputs)``.

    ``rngs`` — a PRNG key or dict of keys threaded to Dropout & friends via
    ``core.random.rng_context``; required for stochastic layers under jit.
    """
    with bind_params(layer, params, buffers=buffers):
        with random_mod.rng_context(rngs):
            return layer(*args, **kwargs)


def module_fn(layer: Layer, method: Optional[str] = None):
    """Return a pure ``fn(params, *args, rngs=None, **kw)`` for jitting."""

    def fn(params, *args, rngs=None, **kwargs):
        with bind_params(layer, params):
            with random_mod.rng_context(rngs):
                target = getattr(layer, method) if method else layer
                return target(*args, **kwargs)

    return fn
