"""paddle.Tensor method surface installed onto jax.Array (parity:
python/paddle/tensor/ methods generated onto the Tensor pybind class).

The tensor type here IS ``jax.Array`` (see tensor.py) — migrating code
that calls ``x.numpy()``, ``x.cast(...)``, ``x.unsqueeze(...)`` gets
those as real methods, installed once at package import onto the
``jax.Array`` ABC (ArrayImpl inherits from it, so lookup works on every
array). STRICTLY ADDITIVE: a name jax.Array already defines is never
touched, so jax semantics cannot change. In-place mutators (add_,
zero_) have no meaning on immutable device arrays and are not provided
— the _() spelling raises in paddle too when the tensor is a leaf
requiring grad, and the functional forms are one rename away.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np


def _unary(fn):
    return lambda self, name=None: fn(self)


def _binary(fn):
    return lambda self, y, name=None: fn(self, y)


def _numpy(self):
    return _np.asarray(self)


def _cast(self, dtype, name=None):
    from . import dtype as _dtype_mod

    return self.astype(_dtype_mod.convert_dtype(dtype))


def _unsqueeze(self, axis, name=None):
    return jnp.expand_dims(self, axis)


def _numel(self, name=None):
    return self.size


def _detach(self):
    return jax.lax.stop_gradient(self)


def _cpu(self):
    return jax.device_put(self, jax.devices("cpu")[0])


def _cuda(self, device_id=None):
    return jax.device_put(self, jax.devices()[device_id or 0])


def _dim(self):
    return self.ndim


def _t(self, name=None):
    if self.ndim > 2:
        raise ValueError("t() expects a tensor with <= 2 dimensions")
    return self.T


def _scale(self, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    if bias_after_scale:
        return self * scale + bias
    return (self + bias) * scale


def _topk(self, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    x = self if largest else -self
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(x, k)
    if not largest:
        vals = -vals
    if axis not in (-1, self.ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx


def _index_select(self, index, axis=0, name=None):
    return jnp.take(self, index, axis=axis)


def _masked_fill(self, mask, value, name=None):
    return jnp.where(mask, jnp.asarray(value, self.dtype), self)


def _expand(self, shape, name=None):
    out = []
    lead = len(shape) - self.ndim
    for i, s in enumerate(shape):
        if s in (-1, None):
            if i < lead:
                raise ValueError(
                    f"expand: dim {i} is new (input has {self.ndim} "
                    "dims) so -1 has no size to inherit")
            out.append(self.shape[i - lead])
        else:
            out.append(s)
    return jnp.broadcast_to(self, out)


def _tile(self, repeat_times, name=None):
    return jnp.tile(self, repeat_times)


def _split(self, num_or_sections, axis=0, name=None):
    if isinstance(num_or_sections, int):
        return jnp.split(self, num_or_sections, axis=axis)
    sizes = list(num_or_sections)
    if sizes.count(-1) > 1:
        raise ValueError("split: at most one section may be -1")
    if -1 in sizes:
        known = sum(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = self.shape[axis] - known
    offs = _np.cumsum(sizes)[:-1].tolist()
    return jnp.split(self, offs, axis=axis)


def _chunk(self, chunks, axis=0, name=None):
    return jnp.array_split(self, chunks, axis=axis)


def _allclose(self, y, rtol=1e-05, atol=1e-08, equal_nan=False,
              name=None):
    return jnp.allclose(self, y, rtol=rtol, atol=atol,
                        equal_nan=equal_nan)


def _equal_all(self, y, name=None):
    # shapes are static; the VALUE comparison stays traced (works
    # under jit — paddle's equal_all returns a tensor too)
    if self.shape != y.shape:
        return jnp.asarray(False)
    return (self == y).all()


def _stop_gradient_get(self):
    # plain data arrays are constants to autodiff (paddle's default
    # True); Parameters — the trainable leaves — carry their own
    # trainable flag. Assignment is meaningless on an immutable array.
    return True


def _stop_gradient_set(self, value):
    if value:
        # x.stop_gradient = True is the most common paddle idiom and a
        # semantic no-op here: plain arrays already ARE constants to
        # autodiff. Only asking for False (tape-style trainability)
        # warrants the migration error.
        return
    raise AttributeError(
        "jax arrays are immutable constants to autodiff; trainability "
        "lives on Parameter.trainable (gradients are explicit "
        "transforms, not tape state)")


_METHODS = {
    "numpy": _numpy,
    "cast": _cast,
    "unsqueeze": _unsqueeze,
    "numel": _numel,
    "detach": _detach,
    "cpu": _cpu,
    "cuda": _cuda,
    "dim": _dim,
    "t": _t,
    "scale": _scale,
    "topk": _topk,
    "index_select": _index_select,
    "masked_fill": _masked_fill,
    "expand": _expand,
    "tile": _tile,
    "split": _split,
    "chunk": _chunk,
    "equal_all": _equal_all,
    "abs": _unary(jnp.abs),
    "exp": _unary(jnp.exp),
    "log": _unary(jnp.log),
    "log2": _unary(jnp.log2),
    "log10": _unary(jnp.log10),
    "log1p": _unary(jnp.log1p),
    "sqrt": _unary(jnp.sqrt),
    "rsqrt": _unary(lambda x: jax.lax.rsqrt(x)),
    "sin": _unary(jnp.sin),
    "cos": _unary(jnp.cos),
    "tan": _unary(jnp.tan),
    "tanh": _unary(jnp.tanh),
    "sigmoid": _unary(jax.nn.sigmoid),
    "floor": _unary(jnp.floor),
    "ceil": _unary(jnp.ceil),
    "sign": _unary(jnp.sign),
    "erf": _unary(jax.scipy.special.erf),
    "neg": _unary(jnp.negative),
    "reciprocal": _unary(jnp.reciprocal),
    "isnan": _unary(jnp.isnan),
    "isinf": _unary(jnp.isinf),
    "isfinite": _unary(jnp.isfinite),
    "add": _binary(jnp.add),
    "subtract": _binary(jnp.subtract),
    "multiply": _binary(jnp.multiply),
    "divide": _binary(jnp.divide),
    "floor_divide": _binary(jnp.floor_divide),
    "mod": _binary(jnp.remainder),
    "remainder": _binary(jnp.remainder),
    "pow": _binary(jnp.power),
    # NOT "dot": jax.Array already defines .dot (matmul semantics), and
    # the additive-only rule forbids overriding it; paddle's per-row
    # dot lives at paddle_tpu.dot (tensor.py)
    "matmul": _binary(jnp.matmul),
    "mm": _binary(jnp.matmul),
    "maximum": _binary(jnp.maximum),
    "minimum": _binary(jnp.minimum),
    "allclose": _allclose,
    "equal": _binary(jnp.equal),
    "not_equal": _binary(jnp.not_equal),
    "greater_than": _binary(jnp.greater),
    "greater_equal": _binary(jnp.greater_equal),
    "less_than": _binary(jnp.less),
    "less_equal": _binary(jnp.less_equal),
    "logical_and": _binary(jnp.logical_and),
    "logical_or": _binary(jnp.logical_or),
}


def install():
    """Install the paddle method surface onto jax.Array — additive
    only, idempotent. Concrete arrays (ArrayImpl) find methods through
    the jax.Array ABC; TRACERS route attribute lookup through their
    aval, so each method is also registered on ShapedArray via jax's
    own aval_method mechanism (the exact machinery jax uses for .sum) —
    migrating method calls keep working inside jit/grad."""
    try:
        from jax._src import core as _core

        shaped = _core.ShapedArray
        aval_method = _core.aval_method
    except (ImportError, AttributeError):  # private-API drift
        shaped = aval_method = None
    # on older jax (<= 0.4.x) the concrete ArrayImpl is only REGISTERED
    # with the jax.Array ABC, not a subclass — attributes set on the ABC
    # never reach instances, so install on the concrete class too
    targets = [jax.Array]
    try:
        from jax._src.array import ArrayImpl as _impl

        if not issubclass(_impl, jax.Array) or \
                jax.Array not in _impl.__mro__:
            targets.append(_impl)
    except (ImportError, AttributeError):
        pass
    for cls in targets:
        for name, fn in _METHODS.items():
            if not hasattr(cls, name):
                setattr(cls, name, fn)
                if (cls is jax.Array and shaped is not None
                        and not hasattr(shaped, name)):
                    setattr(shaped, name, aval_method(fn))
        if not hasattr(cls, "stop_gradient"):
            try:
                cls.stop_gradient = property(_stop_gradient_get,
                                             _stop_gradient_set)
                if cls is jax.Array and shaped is not None:
                    shaped.stop_gradient = _core.aval_property(
                        _stop_gradient_get)
            except (AttributeError, TypeError):
                pass
        if not hasattr(cls, "place"):
            try:
                cls.place = property(
                    lambda self: next(iter(self.devices())))
            except (AttributeError, TypeError):
                pass
