"""Parameter: a named, trainable array slot with sharding metadata.

Parity: paddle's ``EagerParamBase`` (python/paddle/base/framework.py) —
a tensor that knows its name, trainability and distribution attributes.

TPU-native design: a ``Parameter`` is a thin mutable cell around a
``jax.Array``. Layers hold Parameters as attributes (eager ergonomics,
``layer.weight`` works in math expressions via ``__jax_array__`` and
operator overloads); the functional bridge (``core.functional``) swaps the
``.value`` fields for tracers when building jitted train steps, so a
Parameter never needs to be a pytree leaf itself.

Sharding metadata: ``spec`` is a logical partition hint — a tuple with one
entry per dim, each entry a mesh-axis name (e.g. "tp"), a tuple of axis
names, or None. The sharding engine (distributed/sharding.py) combines it
with the active strategy (e.g. adds the fsdp axis for ZeRO-3) to produce
the final ``PartitionSpec``.
"""

from __future__ import annotations

import operator
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_param_counter = [0]


def _auto_name(prefix="param"):
    _param_counter[0] += 1
    return f"{prefix}_{_param_counter[0]}"


class Parameter:
    __slots__ = (
        "value",
        "name",
        "trainable",
        "spec",
        "is_distributed",
        "no_sync",
        "init_fn",
        "optimize_attr",
        "grad",
    )

    def __init__(
        self,
        value: jax.Array,
        name: Optional[str] = None,
        trainable: bool = True,
        spec: Optional[Tuple] = None,
        is_distributed: bool = False,
        init_fn=None,
    ):
        self.value = value
        self.name = name or _auto_name()
        self.trainable = trainable
        # logical per-dim sharding hint; resolved by the sharding engine
        self.spec = spec
        # parity: fleet marks TP-partitioned params is_distributed=True so DP
        # allreduce / broadcast skips them
        self.is_distributed = is_distributed
        self.no_sync = False
        self.init_fn = init_fn
        self.optimize_attr = {"learning_rate": 1.0}
        # populated by autograd.backward (parity: EagerParamBase.grad)
        self.grad = None

    # ---- array protocol -------------------------------------------------
    def __jax_array__(self):
        return self.value

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def ndim(self):
        return self.value.ndim

    @property
    def size(self):
        return self.value.size

    @property
    def T(self):
        return self.value.T

    def astype(self, dtype):
        return self.value.astype(dtype)

    def numpy(self):
        return jax.device_get(self.value)

    def item(self):
        return self.value.item()

    def __len__(self):
        return len(self.value)

    def __getitem__(self, idx):
        return self.value[idx]

    def __iter__(self):
        return iter(self.value)

    def __repr__(self):
        return (
            f"Parameter(name={self.name!r}, shape={tuple(self.value.shape)}, "
            f"dtype={self.value.dtype}, trainable={self.trainable}, "
            f"spec={self.spec})"
        )

    # ---- mutation -------------------------------------------------------
    def set_value(self, v):
        self.value = jnp.asarray(v, dtype=self.value.dtype)

    def stop_gradient_(self, flag: bool = True):
        self.trainable = not flag

    @property
    def stop_gradient(self):
        return not self.trainable

    @stop_gradient.setter
    def stop_gradient(self, flag):
        self.trainable = not flag


def _binop(op, reflected=False):
    if reflected:

        def fn(self, other):
            return op(_unwrap(other), self.value)

    else:

        def fn(self, other):
            return op(self.value, _unwrap(other))

    return fn


def _unwrap(x):
    return x.value if isinstance(x, Parameter) else x


for _name, _op in [
    ("add", operator.add),
    ("sub", operator.sub),
    ("mul", operator.mul),
    ("truediv", operator.truediv),
    ("floordiv", operator.floordiv),
    ("mod", operator.mod),
    ("pow", operator.pow),
    ("matmul", operator.matmul),
]:
    setattr(Parameter, f"__{_name}__", _binop(_op))
    setattr(Parameter, f"__r{_name}__", _binop(_op, reflected=True))

for _name, _op in [
    ("neg", operator.neg),
    ("pos", operator.pos),
    ("abs", operator.abs),
]:
    setattr(Parameter, f"__{_name}__", lambda self, _op=_op: _op(self.value))

for _name, _op in [
    ("lt", operator.lt),
    ("le", operator.le),
    ("gt", operator.gt),
    ("ge", operator.ge),
]:
    setattr(Parameter, f"__{_name}__", _binop(_op))


class ParamAttr:
    """Parameter attribute bundle (parity: paddle.ParamAttr,
    python/paddle/base/param_attr.py): carried through every layer's
    ``weight_attr``/``bias_attr``. ``initializer`` and ``trainable``
    take effect at ``Layer.create_parameter``; ``learning_rate`` lands
    in ``Parameter.optimize_attr`` (read by optimizers the way phi's
    fused kernels read per-param lr scaling); ``regularizer`` /
    ``need_clip`` / ``do_model_average`` are stored for API parity —
    global weight-decay + clip already cover their common use on the
    TPU path."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip
