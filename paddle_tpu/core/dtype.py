"""Dtype registry and default-dtype management.

Parity: paddle's ``paddle.set_default_dtype`` / ``paddle.get_default_dtype``
(upstream: python/paddle/framework/framework.py) and the DataType enum in
paddle/phi/common/data_type.h. On TPU the canonical compute dtype is
bfloat16; fp32 remains the default parameter dtype so that master-weight
semantics match the reference's ``multi_precision`` behavior.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype aliases (paddle.float32 etc. re-exported at package root).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_default_dtype = jnp.float32


def set_default_dtype(d) -> None:
    """Set the default floating dtype used for new parameters/tensors."""
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype(d):
    """Normalize a string / numpy / jax dtype spec to a jnp dtype."""
    if d is None:
        return _default_dtype
    if isinstance(d, str):
        if d not in _STR_TO_DTYPE:
            raise ValueError(f"unknown dtype string: {d!r}")
        return _STR_TO_DTYPE[d]
    return jnp.dtype(d).type if isinstance(d, np.dtype) else d


def is_floating_dtype(d) -> bool:
    return jnp.issubdtype(jnp.dtype(d), jnp.floating)
