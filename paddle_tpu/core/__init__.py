from . import dtype, functional, initializer, meta, random
from .functional import (
    bind_params,
    extract_buffers,
    extract_param_objs,
    extract_params,
    functional_call,
    module_fn,
)
from .module import Layer
from .parameter import Parameter

__all__ = [
    "Layer", "Parameter", "dtype", "random", "initializer", "functional",
    "functional_call", "extract_params", "extract_param_objs",
    "extract_buffers", "bind_params", "module_fn",
]
