"""RNG management: global seeding, named RNG state trackers, and a
jit-pure key-threading context.

Parity targets (upstream layout):
  - ``paddle.seed`` (python/paddle/framework/random.py)
  - ``fleet.meta_parallel.get_rng_state_tracker`` — named RNG trees so that
    tensor-parallel ranks can draw *different* dropout masks inside the TP
    region ("local_seed") while sharing identical masks elsewhere
    ("global_seed") (python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py).

TPU-native design: instead of stateful cuRAND generators, everything reduces
to ``jax.random`` keys. Eager-mode calls draw from a deterministic global
counter; inside a jitted function the caller threads an explicit key via
``rng_context`` (see ``core.functional.functional_call``'s ``rngs`` arg) and
layers derive per-call subkeys with ``fold_in`` on a trace-time counter, so
the program stays pure and retrace-stable.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

import jax
import numpy as np

_state = threading.local()


def _ensure_state():
    if not hasattr(_state, "seed"):
        _state.seed = 0
        _state.counter = 0
        _state.ctx_stack = []
    return _state


def seed(s: int) -> None:
    """Set the global seed (parity: ``paddle.seed``)."""
    st = _ensure_state()
    st.seed = int(s)
    st.counter = 0


def get_seed() -> int:
    return _ensure_state().seed


def default_key() -> jax.Array:
    """Draw a fresh deterministic key from the global eager-mode
    stream. ``PT_FLAGS_rng_use_global_seed=off`` swaps the stream's
    base for a once-per-thread OS-entropy seed — explicitly
    non-reproducible runs (the reference's unseeded-generator mode)."""
    from .. import flags

    st = _ensure_state()
    base = st.seed
    if not flags.flag("rng_use_global_seed"):
        if not hasattr(_state, "entropy_seed"):
            import secrets

            _state.entropy_seed = secrets.randbits(63)
        base = _state.entropy_seed
    key = jax.random.fold_in(jax.random.PRNGKey(base), st.counter)
    st.counter += 1
    return key


class _RngFrame:
    """One active rng scope: a base key plus per-tag fold counters."""

    __slots__ = ("keys", "counters")

    def __init__(self, keys: Dict[str, jax.Array]):
        self.keys = keys
        self.counters: Dict[str, int] = {}

    def next_key(self, tag: str) -> jax.Array:
        if tag in self.keys:
            base = self.keys[tag]
        else:
            if "default" in self.keys:
                base = self.keys["default"]
            else:
                # fall back to any stream deterministically
                base = next(iter(self.keys.values()))
            # decorrelate tags sharing a fallback base: fold a stable tag
            # hash in before the per-tag counter (zlib.crc32 — str hash()
            # is salted per process)
            import zlib

            base = jax.random.fold_in(
                base, zlib.crc32(tag.encode()) & 0x7FFFFFFF
            )
        c = self.counters.get(tag, 0)
        self.counters[tag] = c + 1
        return jax.random.fold_in(base, c)


@contextlib.contextmanager
def rng_context(rngs):
    """Bind explicit PRNG keys for the duration of a (possibly traced) call.

    ``rngs`` may be a single key or a dict ``{tag: key}`` (tags like
    "dropout", "params", "global_seed", "local_seed").
    """
    if rngs is None:
        yield
        return
    if not isinstance(rngs, dict):
        rngs = {"default": rngs}
    st = _ensure_state()
    frame = _RngFrame(dict(rngs))
    st.ctx_stack.append(frame)
    try:
        yield frame
    finally:
        st.ctx_stack.pop()


def next_rng_key(tag: str = "default") -> jax.Array:
    """Get a fresh subkey for ``tag``.

    Inside an active ``rng_context`` (i.e. inside a functional/jitted call)
    this folds a trace-time counter into the bound key — pure and
    deterministic. Outside, it draws from the eager global stream.
    """
    st = _ensure_state()
    if st.ctx_stack:
        return st.ctx_stack[-1].next_key(tag)
    return default_key()


def has_rng_context() -> bool:
    return bool(_ensure_state().ctx_stack)


class RNGStatesTracker:
    """Named RNG state trees (parity: ``get_rng_state_tracker``).

    Tensor-parallel models register a "local_seed" (different per TP rank,
    used for dropout inside partitioned regions) and a "global_seed"
    (identical across TP ranks). Here each named state is just a distinct
    fold of the base seed; ``add`` records the seed, and ``rng_state``
    scopes a context so ``next_rng_key`` draws from that stream.
    """

    def __init__(self):
        self.states_: Dict[str, int] = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name: str, seed_val: int):
        if seed_val in self.seeds_:
            raise ValueError(f"seed {seed_val} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed_val)
        self.states_[name] = seed_val

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)
        self.seeds_ = set(states.values())

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        base = jax.random.PRNGKey(self.states_[name])
        with rng_context({"default": base, "dropout": base}):
            yield


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed_val: int, tp_rank: int = 0):
    """Initialize the tracker the way Fleet does: a global stream shared by
    all TP ranks and a local stream offset by the TP rank."""
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("global_seed", seed_val)
    tracker.add("local_seed", seed_val + 1024 + tp_rank)


def uniform(shape, dtype=None, min=0.0, max=1.0):  # noqa: A002
    from .dtype import convert_dtype

    return jax.random.uniform(
        next_rng_key("uniform"), shape, convert_dtype(dtype), min, max
    )


def normal(shape, dtype=None, mean=0.0, std=1.0):
    from .dtype import convert_dtype

    return mean + std * jax.random.normal(
        next_rng_key("normal"), shape, convert_dtype(dtype)
    )


def randint(low, high=None, shape=(), dtype="int64"):
    from .dtype import convert_dtype

    if high is None:
        low, high = 0, low
    return jax.random.randint(
        next_rng_key("randint"), shape, low, high, convert_dtype(dtype)
    )


def randperm(n: int, dtype="int64"):
    from .dtype import convert_dtype

    return jax.random.permutation(next_rng_key("randperm"), n).astype(
        convert_dtype(dtype)
    )


def shuffle_numpy(arr: np.ndarray, epoch_seed: int) -> np.ndarray:
    """Host-side deterministic shuffle used by the data pipeline."""
    rng = np.random.default_rng(epoch_seed)
    perm = rng.permutation(len(arr))
    return arr[perm]
