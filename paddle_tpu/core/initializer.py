"""Parameter initializers.

Parity: paddle.nn.initializer (upstream: python/paddle/nn/initializer/) —
Constant, Normal, TruncatedNormal, Uniform, XavierNormal/Uniform,
KaimingNormal/Uniform. Each initializer is a callable
``(key, shape, dtype) -> jax.Array`` so it can be used both eagerly at
parameter-creation time and functionally under jit (e.g. for re-init).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


class Initializer:
    def __call__(self, key, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype):
        # Sample in fp32 then cast: bf16 sampling loses too much entropy.
        x = self.mean + self.std * jax.random.normal(key, shape, jnp.float32)
        return x.astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, key, shape, dtype):
        x = jax.random.truncated_normal(key, self.a, self.b, shape, jnp.float32)
        return (self.mean + self.std * x).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype):
        x = jax.random.uniform(key, shape, jnp.float32, self.low, self.high)
        return x.astype(dtype)


def _fan_in_out(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weights are [in_features, out_features]
        return shape[0], shape[1]
    receptive = math.prod(shape[2:])
    # conv weight [out_c, in_c, *k] (paddle layout)
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        std = self.gain * math.sqrt(2.0 / (fan_in + fan_out))
        return Normal(0.0, std)(key, shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape)
        limit = self.gain * math.sqrt(6.0 / (fan_in + fan_out))
        return Uniform(-limit, limit)(key, shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _gain(self):
        if self.nonlinearity == "leaky_relu":
            return math.sqrt(2.0 / (1 + self.negative_slope**2))
        return math.sqrt(2.0)

    def __call__(self, key, shape, dtype):
        fan_in = self.fan_in or _fan_in_out(shape)[0]
        std = self._gain() / math.sqrt(fan_in)
        return Normal(0.0, std)(key, shape, dtype)


class KaimingUniform(KaimingNormal):
    def __call__(self, key, shape, dtype):
        fan_in = self.fan_in or _fan_in_out(shape)[0]
        limit = self._gain() * math.sqrt(3.0 / fan_in)
        return Uniform(-limit, limit)(key, shape, dtype)


class Orthogonal(Initializer):
    """Parity: paddle.nn.initializer.Orthogonal — QR of a gaussian,
    sign-fixed; trailing dims flattened for >2-D shapes."""

    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal needs >= 2 dims")
        rows = shape[0]
        cols = int(math.prod(shape[1:]))
        a = jax.random.normal(
            key, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(a)          # q: [max, min], orthonormal cols
        q = q * jnp.sign(jnp.diagonal(r))[None, :]
        if rows < cols:
            q = q.T                      # → [rows(min), cols(max)]
        return (self.gain * q.reshape(shape)).astype(dtype)


class Dirac(Initializer):
    """Parity: paddle.nn.initializer.Dirac — identity-preserving conv
    kernels ([out, in, *k]); channel i passes input channel i % in
    through the kernel center."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, key, shape, dtype):
        if len(shape) < 3:
            raise ValueError("Dirac needs a conv kernel shape")
        out_c, in_c = shape[0], shape[1]
        w = jnp.zeros(shape, dtype)
        centers = tuple(k // 2 for k in shape[2:])
        opg = out_c // self.groups
        # reference (torch dirac_/paddle Dirac): within each group only
        # the first min(out_per_group, in) channels get an identity tap;
        # the rest stay zero (no modular wrap). One batched scatter, not
        # a per-channel eager loop.
        import numpy as _np

        os_ = _np.arange(out_c)
        ds = os_ % opg
        sel = ds < in_c
        idx = (os_[sel], ds[sel]) + tuple(
            _np.full(sel.sum(), c) for c in centers)
        return w.at[idx].set(1.0)


class Assign(Initializer):
    """Parity: paddle.nn.initializer.Assign — fixed array/list value."""

    def __init__(self, value):
        import numpy as _np

        self.value = _np.asarray(value)

    def __call__(self, key, shape, dtype):
        if tuple(self.value.shape) != tuple(shape):
            raise ValueError(
                f"Assign: value shape {self.value.shape} != {shape}")
        return jnp.asarray(self.value, dtype)


class Bilinear(Initializer):
    """Parity: paddle.nn.initializer.Bilinear — upsampling deconv
    kernels."""

    def __call__(self, key, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear expects [out, in, kh, kw]")
        kh, kw = shape[2], shape[3]

        def ramp(k):
            f = (k + 1) // 2
            c = (2 * f - 1 - f % 2) / (2.0 * f)
            return (1 - jnp.abs(jnp.arange(k) / f - c))

        kern = ramp(kh)[:, None] * ramp(kw)[None, :]
        # reference fills EVERY (out, in) filter with the ramp kernel
        w = jnp.broadcast_to(kern, shape)
        return w.astype(dtype)


def calculate_gain(nonlinearity, param=None):
    """Parity: paddle.nn.initializer.calculate_gain."""
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d",
                       "conv_transpose1d", "conv_transpose2d",
                       "conv_transpose3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else float(param)
        return math.sqrt(2.0 / (1 + a * a))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    raise ValueError(f"unknown nonlinearity {nonlinearity!r}")


def _linear_default_weight_init():
    # paddle's default for Linear: XavierNormal-like (upstream uses
    # XavierNormal for most layers via default_initializer on create_parameter)
    return XavierNormal()


def resolve(init, default=None) -> Initializer:
    if init is None:
        return default or XavierNormal()
    if isinstance(init, Initializer):
        return init
    if callable(init):

        class _Wrap(Initializer):
            def __call__(self, key, shape, dtype):
                return init(key, shape, dtype)

        return _Wrap()
    raise TypeError(f"cannot interpret initializer: {init!r}")
