"""Abstract ("meta") parameter initialization.

Parity: ``paddle.LazyGuard`` (upstream: python/paddle/nn/initializer/
lazy_init.py) — construct a Layer tree without allocating parameter
storage, so a 70B-parameter model can be *described* on a host that could
never hold it.

TPU-native design: the placeholder is ``jax.ShapeDtypeStruct``, which
every JAX AOT entry point (``jax.eval_shape``, ``jit(...).lower``)
accepts directly. A meta-constructed model can therefore be lowered and
compiled against a ``jax.sharding.Mesh`` — per-device HBM planning via
``compiled.memory_analysis()`` — with zero bytes of parameter memory,
where the reference's LazyGuard only defers to a later ``initialize()``.
The ``init_fn`` each Parameter keeps means the tree can still be
materialized later (``materialize``), matching LazyInit's contract.
"""

from __future__ import annotations

import contextlib

import jax

from . import dtype as dtype_mod
from . import initializer as init_mod
from .module import Layer
from .parameter import Parameter

_ACTIVE = [False]


def in_meta_init() -> bool:
    return _ACTIVE[0]


@contextlib.contextmanager
def meta_init():
    """Inside this context, ``Layer.create_parameter`` produces
    Parameters whose ``.value`` is a ``jax.ShapeDtypeStruct`` — no
    initializer runs, no memory is allocated. Buffers (rope caches,
    norm running stats) stay concrete: they are small and often
    computed, not initialized."""
    orig = Layer.create_parameter

    def create_abstract(self, shape, dtype=None, default_initializer=None,
                        is_bias=False, spec=None, name=None):
        dt = dtype_mod.convert_dtype(dtype or self._dtype)
        default = (init_mod.Constant(0.0) if is_bias
                   else init_mod.XavierNormal())
        init = init_mod.resolve(default_initializer, default)
        value = jax.ShapeDtypeStruct(tuple(int(s) for s in shape), dt)
        return Parameter(value, name=name, spec=spec, init_fn=init)

    Layer.create_parameter = create_abstract
    _ACTIVE[0] = True
    try:
        yield
    finally:
        Layer.create_parameter = orig
        _ACTIVE[0] = False


def is_abstract(value) -> bool:
    return isinstance(value, jax.ShapeDtypeStruct)


def materialize(layer: Layer, seed: int = 0) -> None:
    """Run the kept ``init_fn`` for every abstract Parameter (parity:
    LazyInit's deferred ``initialize()``)."""
    key = jax.random.PRNGKey(seed)
    for _, p in layer.named_parameters():
        if is_abstract(p.value):
            if p.init_fn is None:
                raise RuntimeError(
                    f"meta parameter {p.name!r} has no init_fn")
            key, sub = jax.random.split(key)
            p.value = p.init_fn(sub, tuple(p.value.shape), p.value.dtype)
