"""Sparse layers (parity: python/paddle/sparse/nn/).

The reference ships ReLU/BatchNorm/Conv3D for point-cloud workloads
(paddle/phi/kernels/sparse/). Point-cloud submanifold conv is a
gather/scatter workload with data-dependent patterns — a poor fit for the
MXU — so we provide the activation/norm layers over BCOO values and leave
Conv3D as a documented densify-and-conv fallback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.module import Layer

__all__ = ["ReLU", "LeakyReLU", "Softmax", "BatchNorm"]


class ReLU(Layer):
    def forward(self, x):
        from . import map_values

        return map_values(x, jax.nn.relu)


class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        from . import map_values

        return map_values(
            x, lambda v: jax.nn.leaky_relu(v, self.negative_slope))


class Softmax(Layer):
    """Row-wise softmax over a sparse matrix's stored entries.

    Parity: paddle.sparse.nn.Softmax (CSR row softmax). Computed on the
    COO form with a segment-softmax over row ids.
    """

    def __init__(self, axis: int = -1):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse softmax supports axis=-1 only")

    def forward(self, x):
        from . import _as_bcoo

        x = _as_bcoo(x, coalesce=True)
        if x.n_dense:
            raise ValueError("sparse Softmax expects scalar stored values "
                             f"(n_dense=0); got n_dense={x.n_dense}")
        # group by ALL leading sparse dims — softmax normalizes over the
        # last axis only, whatever the tensor rank.
        lead = x.indices[:, :-1].astype(jnp.int32)
        n_groups = 1
        seg = jnp.zeros((x.indices.shape[0],), jnp.int32)
        for d in range(lead.shape[1]):
            seg = seg * x.shape[d] + jnp.clip(lead[:, d], 0, x.shape[d] - 1)
            n_groups *= x.shape[d]
        # padded slots from coalescing carry out-of-range ids; mark them
        # with an out-of-range segment so segment ops drop them.
        valid = jnp.all(x.indices < jnp.array(x.shape), axis=1)
        seg = jnp.where(valid, seg, n_groups)
        segmax = jax.ops.segment_max(x.data, seg, num_segments=n_groups + 1)
        idx = jnp.clip(seg, 0, n_groups)
        shifted = jnp.exp(x.data - segmax[idx])
        denom = jax.ops.segment_sum(shifted, seg, num_segments=n_groups + 1)
        out = shifted / denom[idx]
        return jsparse.BCOO((out, x.indices), shape=x.shape)


class BatchNorm(Layer):
    """BatchNorm over the dense trailing channel of sparse activations.

    Operates on COO tensors whose *values carry a dense channel dim* —
    i.e. ``n_dense >= 1`` with values shaped [nnz, ..., C], the layout
    the reference's sparse batch_norm kernels use for point clouds
    (values [nnz, C] for an [N, D, H, W, C] SparseCooTensor). Tracks
    running statistics; eval mode normalizes with them.
    """

    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5):
        super().__init__()
        from ..core.parameter import Parameter
        self.num_features = num_features
        self.epsilon = epsilon
        self.momentum = momentum
        self.weight = Parameter(jnp.ones((num_features,), jnp.float32))
        self.bias = Parameter(jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer(
            "_variance", jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        from . import _as_bcoo

        x = _as_bcoo(x, coalesce=True)
        if x.n_dense < 1 or x.data.shape[-1] != self.num_features:
            raise ValueError(
                "sparse BatchNorm needs values with a trailing dense "
                f"channel of size {self.num_features} (n_dense>=1); got "
                f"values of shape {x.data.shape} with n_dense={x.n_dense}. "
                "Build the input with to_sparse_coo(dense, sparse_dim=k) "
                "so the channel dim stays dense.")
        v = x.data
        axes = tuple(range(v.ndim - 1))
        if self.training:
            # coalescing pads freed slots with zero values at out-of-range
            # indices; mask them out or they bias the statistics to zero
            n_sparse = x.indices.shape[-1]
            valid = jnp.all(
                x.indices < jnp.array(x.shape[:n_sparse]), axis=-1)
            w = valid.astype(v.dtype).reshape(
                (-1,) + (1,) * (v.ndim - 1))
            n = jnp.maximum(jnp.sum(valid), 1).astype(v.dtype) * (
                v.size // v.shape[0] // self.num_features)
            mean = jnp.sum(v * w, axis=axes) / n
            var = jnp.sum(jnp.square(v - mean) * w, axis=axes) / n
            if not isinstance(mean, jax.core.Tracer):
                # eager only — same contract as dense BatchNorm2D: under
                # jit the running stats stay frozen so no tracer leaks
                # into the buffers
                m = self.momentum
                self._buffers["_mean"] = (
                    m * self._buffers["_mean"] + (1 - m) * mean)
                self._buffers["_variance"] = (
                    m * self._buffers["_variance"] + (1 - m) * var)
        else:
            mean = self._buffers["_mean"]
            var = self._buffers["_variance"]
        out = (v - mean) / jnp.sqrt(var + self.epsilon)
        out = out * self.weight.value + self.bias.value
        return jsparse.BCOO((out, x.indices), shape=x.shape)
