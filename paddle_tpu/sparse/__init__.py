"""Sparse tensor surface (parity: python/paddle/sparse/).

The reference carries COO/CSR tensor types plus a sparse kernel set
(paddle/phi/kernels/sparse/, paddle/phi/core/sparse_coo_tensor.h). On TPU
the honest design is different: XLA has no native sparse execution — the
MXU wants dense tiles — so sparse tensors here are a *representation and
interop* layer built on ``jax.experimental.sparse`` (BCOO/BCSR). Ops keep
data sparse where jax's sparse rules support it (elementwise, dot_general,
reductions) and densify only where unavoidable; under ``jit`` the
sparsity-structure ops trace like any other jax code.

SelectedRows (the reference's embedding-gradient format,
paddle/phi/core/selected_rows.h) is deliberately absent: under XLA,
embedding grads are produced by scatter-add fusion and never materialize a
rows+values pair.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.parameter import Parameter
from . import nn  # noqa: F401  (namespace parity: paddle.sparse.nn)

__all__ = [
    "sparse_coo_tensor",
    "sparse_csr_tensor",
    "to_dense",
    "to_sparse_coo",
    "to_sparse_csr",
    "is_sparse",
    "is_sparse_coo",
    "is_sparse_csr",
    "coalesce",
    "add",
    "subtract",
    "multiply",
    "divide",
    "matmul",
    "masked_matmul",
    "transpose",
    "relu",
    "nnz",
]


def _v(x):
    return x.value if isinstance(x, Parameter) else x


def _as_bcoo(x, coalesce: bool = False):
    """Normalize any sparse operand to BCOO (optionally coalesced)."""
    x = _v(x)
    if isinstance(x, jsparse.BCSR):
        x = x.to_bcoo()
    if coalesce and isinstance(x, jsparse.BCOO):
        # nse is preserved: duplicates are summed and the freed slots
        # padded with out-of-range indices, which todense/ops drop —
        # required so this stays trace-compatible under jit.
        x = jsparse.bcoo_sort_indices(x.sum_duplicates(nse=x.nse))
    return x


# -- construction -----------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Build a COO sparse array from ``[sparse_ndim, nnz]`` indices.

    Mirrors ``paddle.sparse.sparse_coo_tensor`` (reference surface:
    python/paddle/sparse/creation.py). Returns a jax BCOO with n_batch=0,
    n_dense=0 — the direct analog of phi's SparseCooTensor.
    """
    indices = jnp.asarray(_v(indices))
    values = jnp.asarray(_v(values), dtype=dtype)
    if indices.ndim != 2:
        raise ValueError(
            f"indices must be [sparse_ndim, nnz]; got shape {indices.shape}")
    if shape is None:
        if indices.shape[1] == 0 or isinstance(indices, jax.core.Tracer):
            raise ValueError(
                "shape must be given explicitly for empty or traced "
                "indices — it cannot be inferred")
        shape = tuple(int(m) + 1 for m in jnp.max(indices, axis=1))
    # BCOO stores indices as [nnz, sparse_ndim]
    return jsparse.BCOO((values, indices.T.astype(jnp.int32)),
                        shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Build a CSR sparse matrix (parity: paddle.sparse.sparse_csr_tensor)."""
    crows = jnp.asarray(_v(crows), dtype=jnp.int32)
    cols = jnp.asarray(_v(cols), dtype=jnp.int32)
    values = jnp.asarray(_v(values), dtype=dtype)
    if len(shape) != 2:
        raise ValueError("sparse_csr_tensor supports 2-D matrices; "
                         f"got shape {shape}")
    return jsparse.BCSR((values, cols, crows), shape=tuple(shape))


# -- conversion -------------------------------------------------------------

def to_sparse_coo(x, sparse_dim: Optional[int] = None):
    x = _v(x)
    if isinstance(x, jsparse.BCSR):
        return x.to_bcoo()
    if isinstance(x, jsparse.BCOO):
        return x
    n_sparse = sparse_dim if sparse_dim is not None else jnp.ndim(x)
    return jsparse.BCOO.fromdense(jnp.asarray(x), n_dense=jnp.ndim(x) - n_sparse)


def to_sparse_csr(x):
    x = _v(x)
    if isinstance(x, jsparse.BCSR):
        return x
    if isinstance(x, jsparse.BCOO):
        # eager conversion: drop duplicate/padded slots for real (nse
        # shrinks), so the CSR carries only true entries
        return jsparse.BCSR.from_bcoo(
            jsparse.bcoo_sort_indices(x.sum_duplicates()))
    return jsparse.BCSR.fromdense(jnp.asarray(x))


def to_dense(x):
    x = _v(x)
    if isinstance(x, (jsparse.BCOO, jsparse.BCSR)):
        return x.todense()
    return jnp.asarray(x)


def is_sparse(x):
    return isinstance(_v(x), (jsparse.BCOO, jsparse.BCSR))


def is_sparse_coo(x):
    return isinstance(_v(x), jsparse.BCOO)


def is_sparse_csr(x):
    return isinstance(_v(x), jsparse.BCSR)


def nnz(x):
    """Number of stored *in-range* entries.

    After ``coalesce`` the buffer keeps its nse with freed slots padded by
    out-of-range indices; those are not real entries and are not counted
    (parity: Tensor.coalesce shrinks nnz in the reference).
    """
    x = _v(x)
    if isinstance(x, jsparse.BCOO):
        n_sparse = x.indices.shape[-1]
        bound = jnp.array(x.shape[x.n_batch:x.n_batch + n_sparse])
        count = jnp.sum(jnp.all(x.indices < bound, axis=-1))
        return int(count) if not isinstance(count, jax.core.Tracer) else count
    return x.nse


def bcoo_coalesced(x: jsparse.BCOO) -> jsparse.BCOO:
    return _as_bcoo(x, coalesce=True)


def coalesce(x):
    """Sum duplicate indices and sort (parity: Tensor.coalesce)."""
    x = _v(x)
    if isinstance(x, jsparse.BCOO):
        return _as_bcoo(x, coalesce=True)
    return x


# -- math -------------------------------------------------------------------

def _binary(op, x, y):
    x, y = _v(x), _v(y)
    xs, ys = is_sparse(x), is_sparse(y)
    if not xs and not ys:
        return op(x, y)
    # jax sparse rules: sparse+sparse and sparse*dense stay sparse where
    # supported; fall back through sparsify for the rest.
    fn = jsparse.sparsify(op)
    return fn(_as_bcoo(x) if xs else x, _as_bcoo(y) if ys else y)


def add(x, y):
    return _binary(jnp.add, x, y)


def subtract(x, y):
    return _binary(jnp.subtract, x, y)


def multiply(x, y):
    return _binary(jnp.multiply, x, y)


def divide(x, y):
    # division only defined against dense/scalar divisors (as in reference)
    x = _as_bcoo(x)
    if isinstance(x, jsparse.BCOO):
        return jsparse.BCOO((x.data / jnp.asarray(_v(y)), x.indices),
                            shape=x.shape) if jnp.ndim(_v(y)) == 0 else \
            jsparse.sparsify(jnp.divide)(x, jnp.asarray(_v(y)))
    return jnp.divide(x, _v(y))


def matmul(x, y):
    """Sparse @ dense / sparse @ sparse matmul (parity: paddle.sparse.matmul).

    Lowers to ``bcoo_dot_general`` — on TPU this compiles to gather+dense
    dot; for highly-sparse operands that beats densifying first in HBM
    traffic, which is the only win sparsity can buy on this hardware.
    """
    x, y = _as_bcoo(x), _as_bcoo(y)
    return jsparse.sparsify(jnp.matmul)(x, y)


def masked_matmul(x, y, mask):
    """Dense@dense with output sampled at ``mask``'s sparsity pattern.

    Parity: paddle.sparse.masked_matmul (SDDMM). Uses
    ``bcoo_dot_general_sampled`` so only the nse output entries are formed.
    """
    x, y = jnp.asarray(_v(x)), jnp.asarray(_v(y))
    # coalesce: a duplicate mask index would sample the dot twice and
    # todense would sum the copies, doubling the value
    mask = _as_bcoo(to_sparse_coo(mask), coalesce=True)
    dn = (((x.ndim - 1,), (y.ndim - 2,)), ((), ()))
    data = jsparse.bcoo_dot_general_sampled(x, y, mask.indices,
                                            dimension_numbers=dn)
    return jsparse.BCOO((data, mask.indices), shape=mask.shape)


def transpose(x, perm: Sequence[int]):
    x = _as_bcoo(x)
    if isinstance(x, jsparse.BCOO):
        return jsparse.bcoo_transpose(x, permutation=tuple(perm))
    return jnp.transpose(x, tuple(perm))


def map_values(x, fn):
    """Apply ``fn`` elementwise to stored values. Coalesces first: with
    duplicate indices a per-entry nonlinear map would disagree with the
    dense semantics (relu(2) + relu(-3) != relu(2 + -3))."""
    x = _as_bcoo(x, coalesce=True)
    if isinstance(x, jsparse.BCOO):
        return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape)
    return fn(jnp.asarray(x))


def relu(x):
    """Elementwise relu on values (parity: paddle.sparse.nn.ReLU)."""
    return map_values(x, jax.nn.relu)
