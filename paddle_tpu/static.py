"""paddle_tpu.static (parity: the slice of paddle.static that survives in
a jit-only world — InputSpec for export signatures; Program/Executor are
documented N/A in MAPPING.md since there is no second execution mode)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


class InputSpec:
    """Parity: paddle.static.InputSpec — a symbolic tensor signature for
    jit.save / to_static. ``None`` dims mean 'dynamic' and export through
    jax.export symbolic shapes (the StableHLO module stays batch-
    polymorphic); ``to_struct`` resolves them concretely when a fixed
    shape is needed."""

    def __init__(self, shape, dtype="float32", name=None):
        from .core.dtype import convert_dtype

        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name!r})")

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), tensor.dtype, name)

    def to_symbolic_struct(self, prefix="d", scope=None):
        """jax.ShapeDtypeStruct with export-symbolic dims for the None
        entries (batch-polymorphic StableHLO). All specs of one export
        must share ``scope`` — mixing scopes is a jax.export error."""
        from jax import export as jexport

        if None not in self.shape:
            return jax.ShapeDtypeStruct(self.shape, self.dtype)
        spec_str = ", ".join(
            f"{prefix}{i}" if d is None else str(d)
            for i, d in enumerate(self.shape))
        return jax.ShapeDtypeStruct(
            jexport.symbolic_shape(spec_str, scope=scope), self.dtype)

    def to_struct(self, batch_size=None):
        """Resolve to a jax.ShapeDtypeStruct; ``batch_size`` fills a
        leading None dim."""
        shape = list(self.shape)
        for i, d in enumerate(shape):
            if d is None:
                if i == 0 and batch_size is not None:
                    shape[i] = batch_size
                else:
                    raise ValueError(
                        f"InputSpec {self!r}: dynamic dim {i} must be "
                        "resolved before export (pass batch_size, or "
                        "give a concrete shape — StableHLO export is "
                        "shape-specialized)")
        return jax.ShapeDtypeStruct(tuple(shape), self.dtype)


__all__ = ["InputSpec"]
