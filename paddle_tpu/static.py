"""paddle_tpu.static (parity: the slice of paddle.static that survives in
a jit-only world — InputSpec for export signatures; Program/Executor are
documented N/A in MAPPING.md since there is no second execution mode)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


class InputSpec:
    """Parity: paddle.static.InputSpec — a symbolic tensor signature for
    jit.save / to_static. ``None`` dims mean 'dynamic' and export through
    jax.export symbolic shapes (the StableHLO module stays batch-
    polymorphic); ``to_struct`` resolves them concretely when a fixed
    shape is needed."""

    def __init__(self, shape, dtype="float32", name=None):
        from .core.dtype import convert_dtype

        self.shape = tuple(shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name!r})")

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), tensor.dtype, name)

    def to_symbolic_struct(self, prefix="d", scope=None):
        """jax.ShapeDtypeStruct with export-symbolic dims for the None
        entries (batch-polymorphic StableHLO). All specs of one export
        must share ``scope`` — mixing scopes is a jax.export error."""
        from jax import export as jexport

        if None not in self.shape:
            return jax.ShapeDtypeStruct(self.shape, self.dtype)
        spec_str = ", ".join(
            f"{prefix}{i}" if d is None else str(d)
            for i, d in enumerate(self.shape))
        return jax.ShapeDtypeStruct(
            jexport.symbolic_shape(spec_str, scope=scope), self.dtype)

    def to_struct(self, batch_size=None):
        """Resolve to a jax.ShapeDtypeStruct; ``batch_size`` fills a
        leading None dim."""
        shape = list(self.shape)
        for i, d in enumerate(shape):
            if d is None:
                if i == 0 and batch_size is not None:
                    shape[i] = batch_size
                else:
                    raise ValueError(
                        f"InputSpec {self!r}: dynamic dim {i} must be "
                        "resolved before export (pass batch_size, or "
                        "give a concrete shape — StableHLO export is "
                        "shape-specialized)")
        return jax.ShapeDtypeStruct(tuple(shape), self.dtype)


__all__ = ["InputSpec", "name_scope", "program_guard", "Program",
           "default_main_program", "default_startup_program"]


import contextlib as _contextlib  # noqa: E402 (kept near its users)


@_contextlib.contextmanager
def name_scope(prefix=None):
    """Parity: paddle.static.name_scope — op-name prefixing in the
    static graph; surfaces as a jax named scope so the prefix shows up
    in profiles/HLO instead of a ProgramDesc."""
    import jax

    with jax.named_scope(prefix or "scope"):
        yield


@_contextlib.contextmanager
def program_guard(main_program=None, startup_program=None):
    """Parity: paddle.static.program_guard. There is no ProgramDesc —
    jit tracing owns the graph — so this is a structural no-op that
    keeps legacy static-graph call sites importable."""
    yield main_program


class Program:
    """Minimal Program stand-in (parity: paddle.static.Program — a real
    class so isinstance checks in migrating code keep working)."""

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_MAIN = Program()
_STARTUP = Program()


def default_main_program():
    return _MAIN


def default_startup_program():
    return _STARTUP
