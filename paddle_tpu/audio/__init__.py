"""paddle_tpu.audio (parity: python/paddle/audio/ — features + functional;
the backends/datasets subpackages are file-IO utilities upstream and are
served here by paddle_tpu.io + vision.datasets-style local loading)."""

from . import functional  # noqa: F401
from .features import (  # noqa: F401
    MFCC,
    LogMelSpectrogram,
    MelSpectrogram,
    Spectrogram,
)

__all__ = ["functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
