"""paddle_tpu.audio.features (parity: python/paddle/audio/features/layers.py
— Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC).

The STFT front-end is paddle_tpu.signal.stft (gather-framed, XLA Fft);
the mel filterbank and DCT basis are precomputed numpy constants baked
into the layer, so the device-side work per call is |STFT|^power followed
by two matmuls — a shape XLA fuses into a handful of kernels.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.module import Layer
from .. import signal as _signal
from . import functional as F


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = jnp.asarray(
            F.get_window(window, self.win_length, fftbins=True, dtype=dtype)
        )

    def forward(self, x):
        spec = _signal.stft(
            x, self.n_fft, self.hop_length, self.win_length, self.window,
            center=self.center, pad_mode=self.pad_mode, onesided=True,
        )
        return jnp.abs(spec) ** self.power


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(
            n_fft, hop_length, win_length, window, power, center, pad_mode,
            dtype,
        )
        self.n_mels = n_mels
        self.fbank = jnp.asarray(
            F.compute_fbank_matrix(
                sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype
            )
        )  # [n_mels, n_freq]

    def forward(self, x):
        spec = self.spectrogram(x)              # [..., n_freq, frames]
        return jnp.einsum("mf,...ft->...mt", self.fbank, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db=None, dtype: str = "float32"):
        super().__init__()
        self.mel_spectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype,
        )
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self.mel_spectrogram(x)
        return F.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, norm: str = "ortho",
                 n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, ref_value: float = 1.0,
                 amin: float = 1e-10, top_db=None, dtype: str = "float32"):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, "slaney", ref_value, amin,
            top_db, dtype,
        )
        self.dct = jnp.asarray(
            F.create_dct(n_mfcc, n_mels, norm, dtype)
        )  # [n_mels, n_mfcc]

    def forward(self, x):
        logmel = self.log_mel(x)                 # [..., n_mels, frames]
        return jnp.einsum("mk,...mt->...kt", self.dct, logmel)
