"""paddle_tpu.audio.functional (parity: python/paddle/audio/functional/ —
window_function.py + functional.py: get_window, hz_to_mel, mel_to_hz,
mel_frequencies, fft_frequencies, compute_fbank_matrix, power_to_db,
create_dct).

All filterbank/DCT construction is host-side numpy (done once at layer
build time); only the per-frame application (matmul against the fbank /
DCT matrix) runs on device, where it fuses with the STFT output.
"""

from __future__ import annotations

import math

import numpy as np


def get_window(window, win_length: int, fftbins: bool = True,
               dtype="float32"):
    """Parity: paddle.audio.functional.get_window. ``window`` is a name or
    (name, param) tuple; ``fftbins=True`` gives the periodic variant used
    for STFT analysis."""
    if isinstance(window, tuple):
        name, param = window[0], window[1]
    else:
        name, param = window, None
    n = win_length + 1 if fftbins else win_length
    k = np.arange(n)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / (n - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / (n - 1))
             + 0.08 * np.cos(4 * np.pi * k / (n - 1)))
    elif name == "bartlett":
        w = 1.0 - np.abs(2.0 * k / (n - 1) - 1.0)
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = np.ones(n)
    elif name == "triang":
        m = (n + 1) // 2
        ramp = (np.arange(1, m + 1) - 0.5) / (n / 2.0) \
            if n % 2 == 0 else np.arange(1, m + 1) / ((n + 1) / 2.0)
        w = np.concatenate([ramp, ramp[::-1][n % 2 if n % 2 else 0:]])
        w = w[:n]
    elif name == "kaiser":
        beta = 12.0 if param is None else float(param)
        w = np.kaiser(n, beta)
    elif name == "gaussian":
        std = 7.0 if param is None else float(param)
        w = np.exp(-0.5 * ((k - (n - 1) / 2.0) / std) ** 2)
    else:
        raise ValueError(f"get_window: unknown window {name!r}")
    if fftbins:
        w = w[:-1]
    return w.astype(dtype)


def hz_to_mel(freq, htk: bool = False):
    """Hz → mel. htk=False uses the Slaney (librosa/paddle default)
    piecewise scale; htk=True the classic 2595·log10(1+f/700)."""
    freq = np.asarray(freq, np.float64)
    if htk:
        return 2595.0 * np.log10(1.0 + freq / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    log_region = freq >= min_log_hz
    mels = np.where(
        log_region,
        min_log_mel + np.log(np.maximum(freq, min_log_hz) / min_log_hz)
        / logstep,
        mels,
    )
    return mels if mels.ndim else float(mels)


def mel_to_hz(mel, htk: bool = False):
    mel = np.asarray(mel, np.float64)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    log_region = mel >= min_log_mel
    freqs = np.where(
        log_region,
        min_log_hz * np.exp(logstep * (mel - min_log_mel)),
        freqs,
    )
    return freqs if freqs.ndim else float(freqs)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr: int, n_fft: int):
    return np.linspace(0, sr / 2.0, 1 + n_fft // 2)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 50.0, f_max=None,
                         htk: bool = False, norm="slaney",
                         dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]."""
    f_max = f_max if f_max is not None else sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    elif norm is not None:
        weights /= np.maximum(
            np.linalg.norm(weights, ord=norm, axis=1, keepdims=True), 1e-10
        )
    return weights.astype(dtype)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db=80.0):
    """10·log10(S/ref) with floor + dynamic-range clip; device-side."""
    import jax.numpy as jnp

    s = jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


def create_dct(n_mfcc: int, n_mels: int, norm="ortho", dtype="float32"):
    """DCT-II basis [n_mels, n_mfcc] (paddle layout: applied as
    mel.T @ dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)
    basis = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k[None, :])
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(n_mels)
        basis[:, 1:] *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    return basis.astype(dtype)
