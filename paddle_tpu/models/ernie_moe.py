"""ERNIE-style MoE causal LM (parity: the "ERNIE-3.0 / ERNIE-Bot MoE
(expert-parallel via auto_parallel over ICI)" config in BASELINE.json):
a GPT-style backbone whose FFN is a gated mixture-of-experts every
``moe_every`` layers, trained with the GShard aux load-balance loss and
expert parallelism over the mesh."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..distributed.moe import MoELayer
from ..distributed.parallel_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..distributed.sharding import shard_activation
from ..nn import functional as F
from ..nn.layer.common import Dropout, Embedding, LayerList
from ..nn.layer.norm import LayerNorm
from .gpt import GPTAttention, GPTConfig


@dataclasses.dataclass
class ErnieMoEConfig(GPTConfig):
    num_experts: int = 8
    moe_every: int = 2  # every Nth block uses MoE FFN
    top_k: int = 2
    capacity_factor: float = 1.25
    gate: str = "gshard"
    aux_loss_weight: float = 1e-2
    # dropless (no-token-drop) routing: grouped matmuls single-shard,
    # sort-based all-to-all dispatch when the mesh has ep>1
    moe_dropless: bool = False

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("num_experts", 4)
        kw.setdefault("moe_every", 1)
        return cls(**kw)


class ErnieMoEBlock(Layer):
    def __init__(self, config: ErnieMoEConfig, use_moe: bool):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.ln_1 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.use_moe = use_moe
        if use_moe:
            from ..distributed.moe import DroplessMoELayer

            moe_cls = (DroplessMoELayer if config.moe_dropless
                       else MoELayer)
            if config.moe_dropless:
                # dropless routing has no capacity knob; honor the gate
                # choice through its routing width (switch == top-1)
                kw = {"top_k": 1 if config.gate == "switch"
                      else config.top_k}
            else:
                kw = {"gate": config.gate, "top_k": config.top_k,
                      "capacity_factor": config.capacity_factor}
            self.moe = moe_cls(
                config.hidden_size, config.num_experts,
                d_hidden=config.intermediate_size,
                aux_loss_weight=config.aux_loss_weight,
                **kw,
            )
        else:
            self.fc_in = ColumnParallelLinear(
                config.hidden_size, config.intermediate_size,
                weight_attr=init,
            )
            self.fc_out = RowParallelLinear(
                config.intermediate_size, config.hidden_size,
                weight_attr=init,
            )
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        x = x + self.dropout(self.attn(self.ln_1(x)))
        h = self.ln_2(x)
        if self.use_moe:
            y, aux = self.moe(h)
            return x + self.dropout(y), aux
        y = self.fc_out(F.gelu(self.fc_in(h), approximate=True))
        return x + self.dropout(y), 0.0


class ErnieMoEForCausalLM(Layer):
    def __init__(self, config: ErnieMoEConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=init
        )
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=init,
        )
        self.blocks = LayerList([
            ErnieMoEBlock(
                config, use_moe=((i + 1) % config.moe_every == 0)
            )
            for i in range(config.num_hidden_layers)
        ])
        self.ln_f = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.lm_head = ColumnParallelLinear(
            config.hidden_size, config.vocab_size, weight_attr=init,
            has_bias=False,
        )

    def forward(self, input_ids, labels=None):
        b, s = input_ids.shape
        pos = jnp.arange(s)[None, :]
        x = self.embeddings(input_ids) + self.position_embeddings(pos)
        x = shard_activation(x, ("dp", "fsdp"), "sep", None)
        total_aux = 0.0
        for block in self.blocks:
            x, aux = block(x)
            total_aux = total_aux + aux
        x = self.ln_f(x)
        logits = self.lm_head(x)
        if labels is None:
            return logits
        lm_loss = F.cross_entropy(
            logits[:, :-1, :], labels[:, 1:], ignore_index=-100
        )
        return lm_loss + total_aux
