"""Vision Transformer (parity: the ViT-L / PaddleClas config in
BASELINE.json — conv patch-embed + attention path; the reference runs it
through phi conv + attention kernels, here XLA convs + the shared
flash-attention path).

Data layout NHWC internally (TPU-native: channels-last feeds the MXU
without transposes); NCHW accepted at the boundary for parity.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..core.parameter import Parameter
from ..distributed.parallel_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
)
from ..distributed.sharding import shard_activation
from ..nn import functional as F
from ..nn.layer.common import Dropout, Linear
from ..nn.layer.conv import Conv2D
from ..nn.layer.norm import LayerNorm


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    num_classes: int = 1000
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-6
    # None = follow PT_FLAGS_conv_layout (auto: NHWC patch conv on TPU)
    channels_last: "bool | None" = None

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def vit_l(cls, **kw):
        return cls(hidden_size=1024, num_hidden_layers=24,
                   num_attention_heads=16, intermediate_size=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_classes", 10)
        return cls(**kw)


class ViTBlock(Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        h = config.hidden_size
        self.norm1 = LayerNorm(h, config.layer_norm_epsilon)
        self.qkv = ColumnParallelLinear(h, 3 * h)
        self.proj = RowParallelLinear(h, h)
        self.norm2 = LayerNorm(h, config.layer_norm_epsilon)
        self.fc1 = ColumnParallelLinear(h, config.intermediate_size)
        self.fc2 = RowParallelLinear(config.intermediate_size, h)
        self.drop = Dropout(config.dropout)
        self.num_heads = config.num_attention_heads
        self.head_dim = h // config.num_attention_heads

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv(self.norm1(x)).reshape(
            b, s, 3, self.num_heads, self.head_dim
        )
        out = F.scaled_dot_product_attention(
            qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], training=self.training
        )
        x = x + self.drop(self.proj(out.reshape(b, s, h)))
        y = self.fc2(F.gelu(self.fc1(self.norm2(x))))
        return x + self.drop(y)


class ViT(Layer):
    def __init__(self, config: ViTConfig):
        super().__init__()
        self.config = config
        self.patch_embed = Conv2D(
            config.num_channels, config.hidden_size,
            config.patch_size, stride=config.patch_size,
        )
        self.cls_token = self.create_parameter(
            (1, 1, config.hidden_size),
            default_initializer=I.TruncatedNormal(std=0.02),
        )
        self.pos_embed = self.create_parameter(
            (1, config.num_patches + 1, config.hidden_size),
            default_initializer=I.TruncatedNormal(std=0.02),
        )
        from ..nn.layer.common import LayerList

        self.blocks = LayerList(
            [ViTBlock(config) for _ in range(config.num_hidden_layers)]
        )
        self.norm = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.head = Linear(config.hidden_size, config.num_classes)

    def forward(self, pixel_values, labels=None):
        # accepts NCHW (paddle convention); under the channels-last
        # policy the patch conv runs NHWC (TPU-native) and the
        # patches→tokens flatten becomes a pure reshape — the one
        # transpose happens on the small pixel input, not the embedding
        from ..nn import layout

        cl = layout.decide(getattr(self.config, "channels_last", None))
        if cl:
            with layout.channels_last_scope(True):
                x = self.patch_embed(layout.nchw_to_nhwc(pixel_values))
            b, c = x.shape[0], x.shape[-1]
            x = x.reshape(b, -1, c)  # [b, patches, h]
        else:
            x = self.patch_embed(pixel_values)  # [b, h, gh, gw]
            b, c = x.shape[0], x.shape[1]
            x = x.reshape(b, c, -1).transpose(0, 2, 1)  # [b, patches, h]
        cls = jnp.broadcast_to(
            self.cls_token.value, (b, 1, c)
        ).astype(x.dtype)
        x = jnp.concatenate([cls, x], axis=1) + self.pos_embed.value
        x = shard_activation(x, ("dp", "fsdp"), None, None)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        logits = self.head(x[:, 0])
        if labels is None:
            return logits
        return F.cross_entropy(logits, labels)
