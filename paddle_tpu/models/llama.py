"""Llama-family causal LM — the flagship pretraining model.

Parity: PaddleNLP's LlamaForCausalLM running under Fleet hybrid parallel
(the reference's BASELINE 7B/70B configs: paddlenlp/transformers/llama/
modeling.py with fused rope/rms_norm/flash-attn phi kernels,
ColumnParallelLinear/RowParallelLinear from fleet.meta_parallel).

TPU-first construction:
  - all parallelism is declared, not coded: TP via Parameter.spec on the
    qkv/gate/up (column) and o/down (row) projections, ZeRO-3 via the
    sharding engine's fsdp augmentation, sequence/context parallel via
    activation constraints — GSPMD emits the collectives;
  - attention runs through kernels.flash_attention (Pallas on TPU);
  - rope/rmsnorm are XLA-fused jnp (kernels/rope.py rationale);
  - activation recompute per decoder layer via jax.checkpoint with a
    dots-saveable policy (parity: fleet recompute with
    sequence-parallel-aware RNG handled by functional rng_context).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..distributed.parallel_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..distributed.sharding import sequence_parallel_constraint, shard_activation
from ..kernels import flash_attention as fa
from ..kernels.rope import apply_rope, rope_frequencies
from ..nn import functional as F
from ..nn.layer.norm import RMSNorm


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    # sequence-parallel attention mode when mesh sep>1:
    #   "ulysses" — all-to-all heads↔seq exchange (SEP)
    #   "ring"    — ring attention with rotating KV (CP)
    sep_attention: str = "ulysses"
    use_recompute: bool = False
    recompute_policy: str = "dots_with_no_batch_dims_saveable"
    # chunked fused head+CE loss: full [b, s, vocab] f32 logits (the
    # largest train-step activation) never materialize. 0 = off. Leave
    # off when the model fits — the per-chunk dW accumulation + logits
    # recompute cost ~8% of step time at 876M/v5e; turn on (e.g. 512)
    # for large-vocab/long-seq configs where the head dominates peak HBM
    fused_head_loss_chunk: int = 0
    dtype: str = "float32"
    initializer_range: float = 0.02

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def llama2_7b(cls, **kw):
        return cls(hidden_size=4096, intermediate_size=11008,
                   num_hidden_layers=32, num_attention_heads=32, **kw)

    @classmethod
    def llama3_70b(cls, **kw):
        return cls(vocab_size=128256, hidden_size=8192,
                   intermediate_size=28672, num_hidden_layers=80,
                   num_attention_heads=64, num_key_value_heads=8,
                   rope_theta=500000.0, **kw)

    @classmethod
    def tiny(cls, **kw):
        """Test/dryrun config."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)


def _chunk_history_mask(cache_index, s, ctx_len):
    """Chunked-prefill causal mask, shared by both cache modes: slot
    b's chunk occupies absolute rows ``cache_index[b] .. +s-1``, and
    query row r may attend every cache position ``<= r`` (its own
    chunk's earlier rows included — they were just appended). Returns
    ``(rows [b, s], kv_mask [b, 1, s, ctx_len])``."""
    rows = cache_index[:, None] + jnp.arange(
        s, dtype=cache_index.dtype)[None, :]
    kv_idx = jnp.arange(ctx_len)
    kv_mask = kv_idx[None, None, None, :] <= rows[:, None, :, None]
    return rows, kv_mask


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        d = config.head_dim
        init = I.Normal(0.0, config.initializer_range)
        self.q_proj = ColumnParallelLinear(
            h, config.num_attention_heads * d, weight_attr=init, has_bias=False
        )
        self.k_proj = ColumnParallelLinear(
            h, config.num_key_value_heads * d, weight_attr=init, has_bias=False
        )
        self.v_proj = ColumnParallelLinear(
            h, config.num_key_value_heads * d, weight_attr=init, has_bias=False
        )
        self.o_proj = RowParallelLinear(
            config.num_attention_heads * d, h, weight_attr=init, has_bias=False
        )

    def forward(self, x, cos, sin, position_ids=None, kv_cache=None,
                cache_index=None):
        cfg = self.config
        b, s, _ = x.shape
        q = self.q_proj(x).reshape(b, s, cfg.num_attention_heads, cfg.head_dim)
        k = self.k_proj(x).reshape(b, s, cfg.num_key_value_heads, cfg.head_dim)
        v = self.v_proj(x).reshape(b, s, cfg.num_key_value_heads, cfg.head_dim)
        # heads are tp-sharded; keep [b, s, h_tp, d] layout explicit
        q = shard_activation(q, ("dp", "fsdp"), "sep", "tp", None)
        k = shard_activation(k, ("dp", "fsdp"), "sep", "tp", None)
        v = shard_activation(v, ("dp", "fsdp"), "sep", "tp", None)
        if kv_cache is not None:
            from ..distributed.sharding import current_mesh
            from ..inference.paged import (PagedLayerCache, QuantizedKV,
                                           append_kv, dequantize_kv,
                                           paged_attention,
                                           quantize_kv_rows)
            from ..kernels import decode_attention as da

            paged_mode = isinstance(kv_cache[0], PagedLayerCache)
            per_slot = getattr(cache_index, "ndim", 0) == 1
            # fused single-pass decode (PT_FLAGS_fused_decode): RoPE +
            # KV-append + length-pruned attention in one kernel — no
            # separate append_kv program, no rotated-q/k HBM round-trip.
            # Single-token per-slot decode only; under a mesh the
            # GSPMD-partitioned reference path stays in charge.
            fused = s == 1 and (paged_mode or per_slot) \
                and current_mesh() is None
            if fused:
                minor = (kv_cache[0].k_pages.shape[2] if paged_mode
                         else da.contiguous_chunk(kv_cache[0].shape[1]))
                fused = da.fused_decode_active(
                    cfg.head_dim, minor, kv_cache[0].k_pages.dtype
                    if paged_mode else kv_cache[0].dtype)
            if not fused:
                q, k = apply_rope(q, k, cos, sin, position_ids)
            kvh = cfg.num_key_value_heads
            hd = cfg.head_dim
            if fused:
                lens = (kv_cache[1].seq_lens if paged_mode
                        else jnp.asarray(cache_index, jnp.int32))
                pos = (jnp.asarray(position_ids[:, 0], jnp.int32)
                       if position_ids is not None else lens)
                qg = q[:, 0].reshape(b, kvh, cfg.num_attention_heads
                                     // kvh, hd)
                rope_cos = cos.astype(jnp.float32)
                rope_sin = sin.astype(jnp.float32)
                if paged_mode:
                    from ..kernels.paged_attention import (
                        fused_paged_decode_attention,
                    )

                    cache, state = kv_cache
                    if cache.k_scale is not None:
                        # int8 pool: the kernel quantizes the appended
                        # row and returns updated scale arrays — they
                        # ride the cache pytree like the pages do
                        og, kp, vp, ksc, vsc = \
                            fused_paged_decode_attention(
                                qg, k[:, 0], v[:, 0], cache.k_pages,
                                cache.v_pages, state.block_tables,
                                state.seq_lens, pos, rope_cos,
                                rope_sin, k_scale=cache.k_scale,
                                v_scale=cache.v_scale)
                        new_cache = (PagedLayerCache(kp, vp, ksc, vsc),
                                     state)
                    else:
                        og, kp, vp = fused_paged_decode_attention(
                            qg, k[:, 0], v[:, 0], cache.k_pages,
                            cache.v_pages, state.block_tables,
                            state.seq_lens, pos, rope_cos, rope_sin)
                        new_cache = (PagedLayerCache(kp, vp), state)
                else:
                    ck, cv = kv_cache
                    if isinstance(ck, QuantizedKV):
                        og, ckq, cvq, ksc, vsc = \
                            da.fused_contiguous_decode_attention(
                                qg, k[:, 0], v[:, 0], ck.q, cv.q,
                                lens, pos, rope_cos, rope_sin,
                                k_scale=ck.scale, v_scale=cv.scale)
                        new_cache = (QuantizedKV(ckq, ksc),
                                     QuantizedKV(cvq, vsc))
                    else:
                        og, ck, cv = \
                            da.fused_contiguous_decode_attention(
                                qg, k[:, 0], v[:, 0], ck, cv, lens,
                                pos, rope_cos, rope_sin)
                        new_cache = (ck, cv)
                out = og.reshape(b, 1, cfg.num_attention_heads, hd)
            elif paged_mode and per_slot and s > 1:
                # chunked prefill (paged): scatter the chunk's rows
                # through the block table at each slot's own offset
                # (positions past the table drop — the engine points
                # non-participating slots at a max_len sentinel), then
                # attend over the gathered page view with a per-row
                # causal-history mask. Garbage rows past a slot's real
                # tokens sit at HIGHER positions than every real query,
                # so the mask hides them; decode overwrites them later.
                # KNOWN TRADE: gather_kv materializes the full dense
                # [slots, max_ctx] view per layer per chunk — the
                # static shape is what keeps this path at ONE compile
                # for every prompt length. A length-pruned Pallas
                # chunked-prefill kernel (PR-3 style) is the follow-up
                # that removes the traffic without re-specializing.
                from ..inference.paged import append_kv_chunk, gather_kv

                cache, state = kv_cache
                cache = append_kv_chunk(cache, state, k, v, cache_index)
                kg, vg = gather_kv(cache, state)
                _, kv_mask = _chunk_history_mask(
                    cache_index, s, kg.shape[1])
                out = F.scaled_dot_product_attention(
                    q, kg, vg, attn_mask=kv_mask, training=False)
                new_cache = (cache, state)
            elif paged_mode:
                # paged decode (s == 1): write this token's kv into its
                # slot's page, then attend over the gathered page view
                cache, state = kv_cache
                cache = append_kv(cache, state, k, v)
                out = paged_attention(q, cache, state)
                new_cache = (cache, state)
            else:
                ck, cv = kv_cache
                quant = isinstance(ck, QuantizedKV)
                if not quant:
                    k = k.astype(ck.dtype)
                    v = v.astype(cv.dtype)
                if per_slot and s > 1:
                    # chunked prefill (contiguous): slot b's chunk lands
                    # at rows cache_index[b]..+s-1; mode="drop" makes
                    # rows past max_len (the engine's "not prefilling
                    # this call" sentinel) dropped writes, not clamps
                    rows, kv_mask = _chunk_history_mask(
                        cache_index, s, ck.shape[1])
                    bidx = jnp.arange(b)[:, None]
                    if quant:
                        # quantize-on-append: payload + per-row scales
                        # scatter together (scale rows share the drop
                        # semantics of the sentinel rows)
                        kq, ks = quantize_kv_rows(k)
                        vq, vs = quantize_kv_rows(v)
                        ck = QuantizedKV(
                            ck.q.at[bidx, rows].set(kq, mode="drop"),
                            ck.scale.at[bidx, rows].set(ks, mode="drop"))
                        cv = QuantizedKV(
                            cv.q.at[bidx, rows].set(vq, mode="drop"),
                            cv.scale.at[bidx, rows].set(vs, mode="drop"))
                    else:
                        ck = ck.at[bidx, rows].set(k, mode="drop")
                        cv = cv.at[bidx, rows].set(v, mode="drop")
                elif per_slot:
                    # continuous batching: each slot writes at its own
                    # length (s == 1) and masks to its own history
                    bi = jnp.arange(b)
                    if quant:
                        kq, ks = quantize_kv_rows(k[:, 0])
                        vq, vs = quantize_kv_rows(v[:, 0])
                        ck = QuantizedKV(
                            ck.q.at[bi, cache_index].set(kq),
                            ck.scale.at[bi, cache_index].set(ks))
                        cv = QuantizedKV(
                            cv.q.at[bi, cache_index].set(vq),
                            cv.scale.at[bi, cache_index].set(vs))
                    else:
                        ck = ck.at[bi, cache_index].set(k[:, 0])
                        cv = cv.at[bi, cache_index].set(v[:, 0])
                    kv_idx = jnp.arange(ck.shape[1])
                    kv_mask = (kv_idx[None, :] <=
                               cache_index[:, None])[:, None, None, :]
                else:
                    # single shared index: insert current kv block
                    # (one-shot bucketed prefill — int8 caches never
                    # reach here: the engine requires chunked prefill
                    # for them at init)
                    ck = jax.lax.dynamic_update_slice_in_dim(
                        ck, k, cache_index, 1)
                    cv = jax.lax.dynamic_update_slice_in_dim(
                        cv, v, cache_index, 1)
                    # causal within the block AND limited to filled
                    # slots: query at absolute position cache_index+qi
                    # sees kv_idx <= it
                    q_pos = cache_index + jnp.arange(s)  # [s]
                    kv_idx = jnp.arange(ck.shape[1])
                    kv_mask = (kv_idx[None, :] <=
                               q_pos[:, None])[None, None, :, :]
                out = F.scaled_dot_product_attention(
                    q, dequantize_kv(ck), dequantize_kv(cv),
                    attn_mask=kv_mask, training=False
                )
                new_cache = (ck, cv)
        else:
            from ..distributed.sharding import current_mesh

            q, k = apply_rope(q, k, cos, sin, position_ids)
            mesh = current_mesh()
            sep = mesh.shape.get("sep", 1) if mesh is not None else 1
            if sep > 1 and cfg.sep_attention == "ring":
                from ..kernels.ring_attention import ring_attention

                out = ring_attention(q, k, v, mesh=mesh, causal=True)
            elif sep > 1:
                from ..kernels.ulysses import ulysses_attention

                out = ulysses_attention(
                    q, k, v, causal=True, training=self.training,
                    use_flash=cfg.use_flash_attention,
                )
            elif cfg.use_flash_attention:
                out = fa.flash_attention(q, k, v, causal=True,
                                         training=self.training)
            else:
                out = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, training=self.training
                )
            new_cache = None
        out = out.reshape(b, s, cfg.num_attention_heads * cfg.head_dim)
        out = self.o_proj(out)
        return (out, new_cache) if kv_cache is not None else out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.gate_proj = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, weight_attr=init,
            has_bias=False,
        )
        self.up_proj = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, weight_attr=init,
            has_bias=False,
        )
        self.down_proj = RowParallelLinear(
            config.intermediate_size, config.hidden_size, weight_attr=init,
            has_bias=False,
        )

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(
            config.hidden_size, config.rms_norm_eps
        )

    def forward(self, x, cos, sin, position_ids=None, kv_cache=None,
                cache_index=None):
        residual = x
        h = self.input_layernorm(x)
        if kv_cache is not None:
            h, new_cache = self.self_attn(
                h, cos, sin, position_ids, kv_cache, cache_index
            )
        else:
            h = self.self_attn(h, cos, sin, position_ids)
            new_cache = None
        x = residual + h
        residual = x
        h = self.post_attention_layernorm(x)
        h = self.mlp(h)
        x = residual + h
        return (x, new_cache) if kv_cache is not None else x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=I.Normal(0.0, config.initializer_range),
        )
        from ..nn.layer.common import LayerList

        self.layers = LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        cos, sin = rope_frequencies(
            config.head_dim, config.max_position_embeddings, config.rope_theta
        )
        self.register_buffer("rope_cos", cos, persistable=False)
        self.register_buffer("rope_sin", sin, persistable=False)

    def forward(self, input_ids, position_ids=None, kv_caches=None,
                cache_index=None):
        cfg = self.config
        h = self.embed_tokens(input_ids)
        h = shard_activation(h, ("dp", "fsdp"), "sep", None)
        cos = self._buffers["rope_cos"]
        sin = self._buffers["rope_sin"]
        new_caches = [] if kv_caches is not None else None
        for i, layer in enumerate(self.layers):
            if kv_caches is not None:
                h, nc = layer(h, cos, sin, position_ids, kv_caches[i],
                              cache_index)
                new_caches.append(nc)
            elif cfg.use_recompute and self.training:
                fn = partial(layer.__call__, cos=cos, sin=sin,
                             position_ids=position_ids)
                policy = getattr(
                    jax.checkpoint_policies, cfg.recompute_policy, None
                )
                h = jax.checkpoint(fn, policy=policy)(h)
            else:
                h = layer(h, cos, sin, position_ids)
        h = self.norm(h)
        return (h, new_caches) if kv_caches is not None else h


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size,
                weight_attr=I.Normal(0.0, config.initializer_range),
                has_bias=False,
            )

    def logits(self, hidden):
        if self.lm_head is not None:
            return self.lm_head(hidden)
        w = self.model.embed_tokens.weight.value
        return shard_activation(
            hidden @ w.T, ("dp", "fsdp"), None, "tp"
        )

    def forward(self, input_ids, labels=None, position_ids=None,
                kv_caches=None, cache_index=None):
        if kv_caches is not None:
            hidden, new_caches = self.model(
                input_ids, position_ids, kv_caches, cache_index
            )
            return self.logits(hidden), new_caches
        hidden = self.model(input_ids, position_ids)
        if labels is None:
            return self.logits(hidden)
        shift_labels = labels[:, 1:]
        if self.config.fused_head_loss_chunk:
            # chunked head+CE: math-identical to the full-logits path
            # (softmax is row-wise) but peak memory is one seq chunk
            from ..incubate.nn.functional import fused_linear_cross_entropy

            shift_hidden = hidden[:, :-1, :]
            if self.lm_head is not None:
                return fused_linear_cross_entropy(
                    shift_hidden, self.lm_head.weight.value, shift_labels,
                    ignore_index=-100,
                    seq_chunk=self.config.fused_head_loss_chunk)
            return fused_linear_cross_entropy(
                shift_hidden, self.model.embed_tokens.weight.value,
                shift_labels, transpose_weight=True, ignore_index=-100,
                seq_chunk=self.config.fused_head_loss_chunk)
        # next-token LM loss, fp32 softmax over the (tp-sharded) vocab
        shift_logits = self.logits(hidden)[:, :-1, :]
        return F.cross_entropy(shift_logits, shift_labels, ignore_index=-100)

    def init_kv_caches(self, batch_size: int, max_len: int, dtype=None):
        cfg = self.config
        dtype = dtype or jnp.bfloat16
        if jnp.dtype(dtype) == jnp.int8:
            # quantized contiguous caches: int8 payload + per-row f32
            # dequant scales (see inference.paged.QuantizedKV). Zero
            # scales dequantize untouched rows to the same zeros a fp
            # cache starts with.
            from ..inference.paged import QuantizedKV

            def one():
                return QuantizedKV(
                    jnp.zeros((batch_size, max_len,
                               cfg.num_key_value_heads, cfg.head_dim),
                              jnp.int8),
                    jnp.zeros((batch_size, max_len,
                               cfg.num_key_value_heads), jnp.float32))
            return [(one(), one())
                    for _ in range(cfg.num_hidden_layers)]
        return [
            (
                jnp.zeros((batch_size, max_len, cfg.num_key_value_heads,
                           cfg.head_dim), dtype),
                jnp.zeros((batch_size, max_len, cfg.num_key_value_heads,
                           cfg.head_dim), dtype),
            )
            for _ in range(cfg.num_hidden_layers)
        ]


class LlamaPipeBlock(Layer):
    """Single-activation decoder layer for the SPMD pipeline trunk:
    recomputes the (tiny, XLA-constant-folded) rope tables internally so
    the pipelined inter-stage activation is just the hidden states —
    parity with fleet's LlamaForCausalLMPipe per-stage blocks, which
    likewise rebuild rotary tables per stage rather than shipping them."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.block = LlamaDecoderLayer(config)

    def forward(self, x):
        cfg = self.config
        cos, sin = rope_frequencies(
            cfg.head_dim, x.shape[1], cfg.rope_theta)
        return self.block(x, cos, sin)


def llama_pipeline_module(config: LlamaConfig, num_stages: int):
    """Build the flagship model as a PipelineModule (parity:
    PaddleNLP LlamaForCausalLMPipe): tied/untied embedding + L decoder
    blocks (the homogeneous trunk) + final norm + lm head. Drive with
    ``distributed.pipeline.PipelineTrainStep`` under a pp mesh; the loss
    head runs on the last stage inside the 1F1B schedule."""
    from ..distributed.pipeline import (
        LayerDesc,
        PipelineModule,
        SharedLayerDesc,
    )
    from ..nn.layer.norm import RMSNorm as _RMSNorm

    init = I.Normal(0.0, config.initializer_range)
    if config.tie_word_embeddings:
        embed = SharedLayerDesc(
            "embed", VocabParallelEmbedding, config.vocab_size,
            config.hidden_size, weight_attr=init)
        head = SharedLayerDesc(
            "embed", VocabParallelEmbedding, config.vocab_size,
            config.hidden_size, weight_attr=init,
            forward_func=lambda layer, x: x @ layer.weight.value.T)
    else:
        embed = LayerDesc(VocabParallelEmbedding, config.vocab_size,
                          config.hidden_size, weight_attr=init)
        head = LayerDesc(ColumnParallelLinear, config.hidden_size,
                         config.vocab_size, weight_attr=init,
                         has_bias=False)
    descs = (
        [embed]
        + [LayerDesc(LlamaPipeBlock, config)
           for _ in range(config.num_hidden_layers)]
        + [LayerDesc(_RMSNorm, config.hidden_size, config.rms_norm_eps),
           head]
    )
    return PipelineModule(descs, num_stages=num_stages)
