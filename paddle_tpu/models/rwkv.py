"""RWKV (v4-style) — the linear-recurrence LM family named alongside
Mamba in BASELINE.json ("Mamba-2 / RWKV: selective-scan /
linear-recurrence Phi op").

Parity: the reference implements WKV as a custom CUDA kernel
(sequential per-channel recurrence with running-max stabilization).
TPU-native inversion: the stabilized WKV recurrence is ASSOCIATIVE once
the carry includes the segment length (the decay applied when composing
two segments is w·len(right segment)), so it maps onto
``jax.lax.associative_scan`` — a log-depth, MXU/VPU-friendly program XLA
schedules without any sequential loop. Elements are (m, a, b, n):

    m — running max exponent (stability), a — Σ e^{kᵢ−m}·vᵢ,
    b — Σ e^{kᵢ−m}, n — segment length.

    (m₁,a₁,b₁,n₁) ∘ (m₂,a₂,b₂,n₂):
        M  = max(m₁ − w·n₂, m₂)          # left segment decays w per step
        a  = a₁·e^{m₁−w·n₂−M} + a₂·e^{m₂−M}
        b  = b₁·e^{m₁−w·n₂−M} + b₂·e^{m₂−M}
        n  = n₁ + n₂

The per-token "bonus" u (current token weighted e^{u+kₜ}) composes
outside the scan, exactly as the reference kernel does.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..nn import functional as F
from ..nn.layer.common import LayerList


@dataclass
class RWKVConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    intermediate_size: int = 0  # 0 → 4*hidden
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    dtype: str = "float32"

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 32)
        kw.setdefault("num_hidden_layers", 2)
        return cls(**kw)


def wkv_associative(k, v, w, u):
    """Stabilized WKV over [batch, seq, dim].

    k, v: [b, s, d]; w: [d] positive decay; u: [d] current-token bonus.
    Returns [b, s, d]: for each t,
        (Σ_{i<t} e^{−(t−1−i)·w + kᵢ}·vᵢ + e^{u+kₜ}·vₜ) /
        (Σ_{i<t} e^{−(t−1−i)·w + kᵢ}      + e^{u+kₜ})
    """
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    wf = w.astype(jnp.float32)[None, None]
    uf = u.astype(jnp.float32)[None, None]

    m0 = kf
    a0 = vf
    b0 = jnp.ones_like(kf)
    n0 = jnp.ones_like(kf)

    def combine(left, right):
        m1, a1, b1, n1 = left
        m2, a2, b2, n2 = right
        m1d = m1 - wf * n2
        M = jnp.maximum(m1d, m2)
        e1 = jnp.exp(m1d - M)
        e2 = jnp.exp(m2 - M)
        return M, a1 * e1 + a2 * e2, b1 * e1 + b2 * e2, n1 + n2

    m, a, b, _ = jax.lax.associative_scan(
        combine, (m0, a0, b0, n0), axis=1)
    # `a/b/m` at t include tokens 0..t with pure decay weighting; the WKV
    # numerator needs tokens 0..t−1 plus the t-th with bonus u. The
    # inclusive scan at t−1 is exactly Σ_{i<t} e^{−(t−1−i)w+kᵢ} — the
    # canonical v4 statistic (the most recent past token is one decay
    # step old) — so the shift adds no extra decay.
    m_prev = jnp.concatenate(
        [jnp.full_like(m[:, :1], -1e30), m[:, :-1]], axis=1)
    a_prev = jnp.concatenate([jnp.zeros_like(a[:, :1]), a[:, :-1]], axis=1)
    b_prev = jnp.concatenate([jnp.zeros_like(b[:, :1]), b[:, :-1]], axis=1)

    cur = uf + kf
    M = jnp.maximum(m_prev, cur)
    e_prev = jnp.exp(m_prev - M)
    e_cur = jnp.exp(cur - M)
    num = a_prev * e_prev + vf * e_cur
    den = b_prev * e_prev + e_cur
    return (num / jnp.maximum(den, 1e-30)).astype(v.dtype)


def wkv_reference(k, v, w, u):
    """Naive per-step recurrence (the reference CUDA kernel's math) —
    the numeric oracle for the associative form."""
    import numpy as np

    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    w = np.asarray(w, np.float64)
    u = np.asarray(u, np.float64)
    bsz, s, d = k.shape
    out = np.zeros_like(v)
    for bi in range(bsz):
        num = np.zeros(d)
        den = np.zeros(d)
        for t in range(s):
            cur = np.exp(u + k[bi, t])
            out[bi, t] = (num + cur * v[bi, t]) / (den + cur + 1e-30)
            # canonical v4 update: aₜ = e^{−w}·aₜ₋₁ + e^{kₜ}·vₜ — the new
            # token enters undecayed; decay applies from the next step
            decay = np.exp(-w)
            num = decay * num + np.exp(k[bi, t]) * v[bi, t]
            den = decay * den + np.exp(k[bi, t])
    return out


class RWKVTimeMix(Layer):
    """Time mixing (the attention analog): token-shift interpolation +
    WKV recurrence. Parity: RWKV v4 TimeMix."""

    def __init__(self, config: RWKVConfig, layer_id: int):
        super().__init__()
        h = config.hidden_size
        init = I.Normal(0.0, config.initializer_range)
        ratio = layer_id / max(config.num_hidden_layers - 1, 1)
        self.time_decay = self.create_parameter(
            (h,), default_initializer=I.Constant(-1.0 - ratio))
        self.time_first = self.create_parameter(
            (h,), default_initializer=I.Constant(0.3))
        for name in ("time_mix_k", "time_mix_v", "time_mix_r"):
            setattr(self, name, self.create_parameter(
                (h,), default_initializer=I.Constant(0.5)))
        self.key = self.create_parameter((h, h), default_initializer=init)
        self.value = self.create_parameter((h, h), default_initializer=init)
        self.receptance = self.create_parameter(
            (h, h), default_initializer=init)
        self.output = self.create_parameter((h, h), default_initializer=init)

    def forward(self, x):
        # token shift: mix current with previous token
        prev = jnp.pad(x, ((0, 0), (1, 0), (0, 0)))[:, :-1]

        def mix(p):
            return x * p.value + prev * (1 - p.value)

        k = mix(self.time_mix_k) @ self.key.value
        v = mix(self.time_mix_v) @ self.value.value
        r = jax.nn.sigmoid(mix(self.time_mix_r) @ self.receptance.value)
        # softplus keeps the decay positive (stability contract of wkv)
        w = jax.nn.softplus(self.time_decay.value)
        wkv = wkv_associative(k, v, w, self.time_first.value)
        return (r * wkv) @ self.output.value


class RWKVChannelMix(Layer):
    """Channel mixing (the FFN analog). Parity: RWKV v4 ChannelMix."""

    def __init__(self, config: RWKVConfig):
        super().__init__()
        h, inter = config.hidden_size, config.intermediate_size
        init = I.Normal(0.0, config.initializer_range)
        self.time_mix_k = self.create_parameter(
            (h,), default_initializer=I.Constant(0.5))
        self.time_mix_r = self.create_parameter(
            (h,), default_initializer=I.Constant(0.5))
        self.key = self.create_parameter((h, inter),
                                         default_initializer=init)
        self.value = self.create_parameter((inter, h),
                                           default_initializer=init)
        self.receptance = self.create_parameter(
            (h, h), default_initializer=init)

    def forward(self, x):
        prev = jnp.pad(x, ((0, 0), (1, 0), (0, 0)))[:, :-1]
        xk = x * self.time_mix_k.value + prev * (1 - self.time_mix_k.value)
        xr = x * self.time_mix_r.value + prev * (1 - self.time_mix_r.value)
        k = jnp.square(F.relu(xk @ self.key.value))
        r = jax.nn.sigmoid(xr @ self.receptance.value)
        return r * (k @ self.value.value)


class RWKVBlock(Layer):
    def __init__(self, config: RWKVConfig, layer_id: int):
        super().__init__()
        from ..nn.layer.norm import LayerNorm

        self.ln1 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.ln2 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.att = RWKVTimeMix(config, layer_id)
        self.ffn = RWKVChannelMix(config)

    def forward(self, x):
        x = x + self.att(self.ln1(x))
        x = x + self.ffn(self.ln2(x))
        return x


class RWKVForCausalLM(Layer):
    def __init__(self, config: RWKVConfig):
        super().__init__()
        from ..nn.layer.common import Embedding, Linear
        from ..nn.layer.norm import LayerNorm

        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embeddings = Embedding(config.vocab_size, config.hidden_size,
                                    weight_attr=init)
        self.ln_pre = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.blocks = LayerList([
            RWKVBlock(config, i) for i in range(config.num_hidden_layers)
        ])
        self.ln_out = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.head = Linear(config.hidden_size, config.vocab_size,
                           weight_attr=init, bias_attr=False)

    def forward(self, input_ids, labels=None):
        h = self.ln_pre(self.embeddings(input_ids))
        for blk in self.blocks:
            h = blk(h)
        logits = self.head(self.ln_out(h))
        if labels is None:
            return logits
        return F.cross_entropy(
            logits[:, :-1].reshape(-1, self.config.vocab_size),
            labels[:, 1:].reshape(-1))
