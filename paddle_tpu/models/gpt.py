"""GPT-family causal LM (parity: PaddleNLP GPT / ERNIE dense configs
running under Fleet hybrid parallel — pre-LN transformer, learned
positions, GELU MLP; TP via Column/Row parallel projections)."""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..distributed.parallel_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..distributed.sharding import shard_activation
from ..kernels import flash_attention as fa
from ..nn import functional as F
from ..nn.layer.common import Dropout, Embedding, LayerList
from ..nn.layer.norm import LayerNorm


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    # chunked fused head+CE (see LlamaConfig.fused_head_loss_chunk);
    # 0 = off — worth enabling for GPT's 50k vocab at long seq
    fused_head_loss_chunk: int = 0

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, weight_attr=init)
        self.out_proj = RowParallelLinear(h, h, weight_attr=init)
        self.dropout = Dropout(config.attention_probs_dropout_prob)

    def forward(self, x, kv_cache=None, cache_index=None):
        cfg = self.config
        b, s, _ = x.shape
        qkv = self.qkv_proj(x).reshape(
            b, s, 3, cfg.num_attention_heads, cfg.head_dim
        )
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if kv_cache is not None:
            # AOT-Predictor cache protocol: prefill writes the prompt
            # K/V at [0:s] (cache_index 0), a single-token step writes
            # at scalar cache_index and attends over the masked cache.
            # (llama.py additionally implements the per-slot vector
            # index + chunked forms the continuous-batching engine uses)
            if getattr(cache_index, "ndim", 0) == 1:
                raise ValueError(
                    "GPT decode cache supports scalar cache_index only "
                    "(the continuous-batching engine's per-slot vector "
                    "form is implemented for Llama)")
            if cache_index is None:
                cache_index = 0
            ck, cv = kv_cache
            k = k.astype(ck.dtype)
            v = v.astype(cv.dtype)
            ck = jax.lax.dynamic_update_slice_in_dim(
                ck, k, cache_index, 1)
            cv = jax.lax.dynamic_update_slice_in_dim(
                cv, v, cache_index, 1)
            if s > 1 and isinstance(cache_index, int) and cache_index == 0:
                # prefill fast path: s×s causal attention over the
                # prompt only (the full-cache masked form below costs
                # O(s·L) for an L-slot cache)
                out = F.scaled_dot_product_attention(
                    q, k, v, is_causal=True, training=False)
                return (self.out_proj(
                    out.reshape(b, s, cfg.hidden_size)), (ck, cv))
            # chunked form: query i sits at absolute position
            # cache_index + i and may attend to kv_idx <= that
            q_pos = cache_index + jnp.arange(s)              # [s]
            live = (jnp.arange(ck.shape[1])[None, :]
                    <= q_pos[:, None])                       # [s, L]
            bias = jnp.where(live, 0.0, -1e30)[None, None, :, :]
            out = F.scaled_dot_product_attention(
                q, ck, cv, attn_mask=bias, training=False)
            return (self.out_proj(out.reshape(b, s, cfg.hidden_size)),
                    (ck, cv))
        if cfg.use_flash_attention and not (
            self.training and cfg.attention_probs_dropout_prob > 0
        ):
            out = fa.flash_attention(q, k, v, causal=True,
                                     training=self.training)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=cfg.attention_probs_dropout_prob,
                training=self.training,
            )
        return self.out_proj(out.reshape(b, s, cfg.hidden_size))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.ln_1 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, config.layer_norm_epsilon)
        self.fc_in = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, weight_attr=init
        )
        self.fc_out = RowParallelLinear(
            config.intermediate_size, config.hidden_size, weight_attr=init
        )
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, kv_cache=None, cache_index=None):
        if kv_cache is not None:
            a, kv_cache = self.attn(self.ln_1(x), kv_cache, cache_index)
            x = x + a
            h = self.fc_out(
                F.gelu(self.fc_in(self.ln_2(x)), approximate=True))
            return x + h, kv_cache
        x = x + self.dropout(self.attn(self.ln_1(x)))
        h = self.fc_out(F.gelu(self.fc_in(self.ln_2(x)), approximate=True))
        return x + self.dropout(h)


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=init
        )
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=init,
        )
        self.drop = Dropout(config.hidden_dropout_prob)
        self.h = LayerList(
            [GPTBlock(config) for _ in range(config.num_hidden_layers)]
        )
        self.ln_f = LayerNorm(config.hidden_size, config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, kv_caches=None,
                cache_index=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = jnp.arange(s)[None, :]
        x = self.embeddings(input_ids) + self.position_embeddings(position_ids)
        x = shard_activation(x, ("dp", "fsdp"), "sep", None)
        if kv_caches is not None:
            new_caches = []
            for block, cache in zip(self.h, kv_caches):
                x, cache = block(x, cache, cache_index)
                new_caches.append(cache)
            return self.ln_f(x), new_caches
        x = self.drop(x)
        for block in self.h:
            x = block(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        self.lm_head = ColumnParallelLinear(
            config.hidden_size, config.vocab_size,
            weight_attr=I.Normal(0.0, config.initializer_range),
            has_bias=False,
        )

    def forward(self, input_ids, labels=None, position_ids=None,
                kv_caches=None, cache_index=None):
        if kv_caches is not None:
            hidden, caches = self.gpt(input_ids, position_ids,
                                      kv_caches, cache_index)
            return self.lm_head(hidden), caches
        hidden = self.gpt(input_ids, position_ids)
        if labels is not None and self.config.fused_head_loss_chunk:
            from ..incubate.nn.functional import fused_linear_cross_entropy

            return fused_linear_cross_entropy(
                hidden[:, :-1, :], self.lm_head.weight.value,
                labels[:, 1:], ignore_index=-100,
                seq_chunk=self.config.fused_head_loss_chunk)
        logits = self.lm_head(hidden)
        if labels is None:
            return logits
        return F.cross_entropy(
            logits[:, :-1, :], labels[:, 1:], ignore_index=-100
        )

    def init_kv_caches(self, batch_size: int, max_len: int, dtype=None):
        cfg = self.config
        dtype = dtype or jnp.bfloat16
        return [
            (
                jnp.zeros((batch_size, max_len, cfg.num_attention_heads,
                           cfg.head_dim), dtype),
                jnp.zeros((batch_size, max_len, cfg.num_attention_heads,
                           cfg.head_dim), dtype),
            )
            for _ in range(cfg.num_hidden_layers)
        ]
