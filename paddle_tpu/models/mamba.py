"""Mamba-style selective state-space LM.

Parity: the "Mamba-2 / RWKV (selective-scan + linear-recurrence Phi op →
Pallas)" config in BASELINE.json. The reference implements selective scan
as a custom CUDA kernel; the TPU-native formulation is a **parallel
associative scan** (`jax.lax.associative_scan`) over the linear
recurrence h_t = a_t ⊙ h_{t-1} + b_t — the composition (a, b)∘(a', b') =
(a·a', a'·b + b') is associative, so XLA lowers it to a log-depth scan
that keeps the MXU/VPU busy instead of a sequential loop. This is the
standard TPU mapping for S6/linear-attention recurrences; a Pallas
chunked-scan kernel is the follow-up optimization for very long
sequences.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..distributed.parallel_layers import VocabParallelEmbedding
from ..distributed.sharding import shard_activation
from ..nn import functional as F
from ..nn.layer.common import LayerList, Linear
from ..nn.layer.norm import RMSNorm


@dataclasses.dataclass
class MambaConfig:
    vocab_size: int = 50277
    hidden_size: int = 768
    state_size: int = 16
    num_hidden_layers: int = 24
    expand: int = 2
    dt_rank: int = 48  # ceil(hidden/16)
    conv_kernel: int = 4
    rms_norm_eps: float = 1e-5
    # Pallas chunked scan (kernels/selective_scan.py): avoids the
    # [b,s,d,n] HBM blow-up of the associative scan; requires seq len
    # divisible by scan_chunk
    use_chunked_scan: bool = False
    scan_chunk: int = 128

    @property
    def d_inner(self):
        return self.expand * self.hidden_size

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("state_size", 8)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("dt_rank", 4)
        return cls(**kw)


# canonical implementation lives beside the Pallas kernel; re-exported
# here under its historical name
from ..kernels.selective_scan import (  # noqa: E402
    associative_selective_scan as selective_scan,
)


class MambaMixer(Layer):
    def __init__(self, config: MambaConfig):
        super().__init__()
        cfg = config
        d_in = cfg.d_inner
        init = I.Normal(0.0, 0.02)
        self.in_proj = Linear(cfg.hidden_size, 2 * d_in, weight_attr=init,
                              bias_attr=False)
        # depthwise causal conv over the sequence
        self.conv_weight = self.create_parameter(
            (d_in, cfg.conv_kernel), default_initializer=I.Uniform(-0.5, 0.5)
        )
        self.conv_bias = self.create_parameter((d_in,), is_bias=True)
        self.x_proj = Linear(d_in, cfg.dt_rank + 2 * cfg.state_size,
                             weight_attr=init, bias_attr=False)
        self.dt_proj = Linear(cfg.dt_rank, d_in, weight_attr=init)
        self.A_log = self.create_parameter(
            (d_in, cfg.state_size),
            default_initializer=lambda key, shape, dtype: jnp.log(
                jnp.broadcast_to(
                    jnp.arange(1, shape[1] + 1, dtype=jnp.float32), shape
                )
            ),
        )
        self.D = self.create_parameter(
            (d_in,), default_initializer=I.Constant(1.0)
        )
        self.out_proj = Linear(d_in, cfg.hidden_size, weight_attr=init,
                               bias_attr=False)
        self.config = config

    def forward(self, x):
        cfg = self.config
        b, s, _ = x.shape
        xz = self.in_proj(x)
        xs, z = jnp.split(xz, 2, axis=-1)  # [b, s, d_in] each
        # causal depthwise conv along seq
        k = cfg.conv_kernel
        pad = jnp.pad(xs, ((0, 0), (k - 1, 0), (0, 0)))
        w = self.conv_weight.value  # [d_in, k]
        xs = sum(
            pad[:, i:i + s, :] * w[:, i][None, None, :] for i in range(k)
        ) + self.conv_bias.value
        xs = F.silu(xs)
        proj = self.x_proj(xs)
        dt, B, C = jnp.split(
            proj, [cfg.dt_rank, cfg.dt_rank + cfg.state_size], axis=-1
        )
        delta = jax.nn.softplus(self.dt_proj(dt))
        A = -jnp.exp(self.A_log.value.astype(jnp.float32))
        if cfg.use_chunked_scan and s % cfg.scan_chunk == 0:
            from ..kernels.selective_scan import chunked_selective_scan

            y = chunked_selective_scan(
                xs, delta, A, B, C, self.D.value,
                chunk=cfg.scan_chunk,
            ).astype(x.dtype)
        else:
            y = selective_scan(
                xs.astype(jnp.float32), delta.astype(jnp.float32), A,
                B.astype(jnp.float32), C.astype(jnp.float32),
                self.D.value.astype(jnp.float32),
            ).astype(x.dtype)
        return self.out_proj(y * F.silu(z))


class MambaBlock(Layer):
    def __init__(self, config: MambaConfig):
        super().__init__()
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.mixer = MambaMixer(config)

    def forward(self, x):
        return x + self.mixer(self.norm(x))


class MambaForCausalLM(Layer):
    def __init__(self, config: MambaConfig):
        super().__init__()
        self.config = config
        self.embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size
        )
        self.layers = LayerList(
            [MambaBlock(config) for _ in range(config.num_hidden_layers)]
        )
        self.norm_f = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, labels=None):
        x = self.embeddings(input_ids)
        x = shard_activation(x, ("dp", "fsdp"), "sep", None)
        for layer in self.layers:
            x = layer(x)
        x = self.norm_f(x)
        logits = x @ self.embeddings.weight.value.T  # tied
        if labels is None:
            return logits
        return F.cross_entropy(logits[:, :-1], labels[:, 1:])
