"""Diffusion UNet (parity: the ppdiffusers Stable-Diffusion config in
BASELINE.json — UNet2DConditionModel's structure: ResNet blocks with
GroupNorm+SiLU, self/cross attention at low resolutions, timestep
embedding, down/up sampling with skip connections).

TPU-native notes: NCHW at the API (parity), GroupNorm stats in fp32,
attention through the shared scaled-dot-product path (flash kernel on
TPU shapes), convs via lax.conv with bf16-friendly accumulation.

Layout fast path (``nn.layout``): with ``channels_last`` on (auto =
TPU), the forward transposes ONCE at entry, runs the whole
conv/GroupNorm/attention body in NHWC — TPU's native conv layout, so
XLA emits no per-op relayout copies (the round-5 capture burned 40% of
SD-UNet device time on them) — and transposes back at exit. The
norm→SiLU chains dispatch to the fused Pallas GroupNorm kernel
(``kernels/group_norm.py``) in that layout.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax.numpy as jnp

from ..core.module import Layer
from ..nn import functional as F
from ..nn import layout
from ..nn.layer.common import Linear, Upsample
from ..nn.layer.conv import Conv2D
from ..nn.layer.norm import GroupNorm


@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Sequence[int] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: int = 8
    norm_num_groups: int = 32
    sample_size: int = 64
    # None = follow PT_FLAGS_conv_layout (auto: NHWC on TPU); the
    # paddle-facing API stays NCHW either way
    channels_last: Optional[bool] = None

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("in_channels", 4)
        kw.setdefault("out_channels", 4)
        kw.setdefault("block_out_channels", (32, 64))
        kw.setdefault("layers_per_block", 1)
        kw.setdefault("cross_attention_dim", 32)
        kw.setdefault("attention_head_dim", 4)
        kw.setdefault("norm_num_groups", 8)
        kw.setdefault("sample_size", 16)
        return cls(**kw)


def timestep_embedding(timesteps, dim: int, max_period: float = 10000.0):
    half = dim // 2
    freqs = jnp.exp(
        -math.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half
    )
    args = timesteps.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class ResnetBlock(Layer):
    def __init__(self, in_c, out_c, temb_c, groups):
        super().__init__()
        # SiLU fused into the norm (one HBM pass through the Pallas
        # kernel under NHWC; functionally applied on the NCHW path)
        self.norm1 = GroupNorm(groups, in_c, activation="silu")
        self.conv1 = Conv2D(in_c, out_c, 3, padding=1)
        self.time_emb_proj = Linear(temb_c, out_c)
        self.norm2 = GroupNorm(groups, out_c, activation="silu")
        self.conv2 = Conv2D(out_c, out_c, 3, padding=1)
        self.shortcut = Conv2D(in_c, out_c, 1) if in_c != out_c else None

    def forward(self, x, temb):
        h = self.conv1(self.norm1(x))
        t = self.time_emb_proj(F.silu(temb))
        h = h + (t[:, None, None, :] if layout.active()
                 else t[:, :, None, None])
        h = self.conv2(self.norm2(h))
        skip = x if self.shortcut is None else self.shortcut(x)
        return skip + h


class CrossAttnBlock(Layer):
    """Self-attn + cross-attn + GEGLU ff over flattened spatial tokens."""

    def __init__(self, channels, ctx_dim, head_dim, groups):
        super().__init__()
        self.norm = GroupNorm(groups, channels)
        self.proj_in = Linear(channels, channels)
        self.n_heads = max(1, channels // (head_dim * 8)) * 1
        self.n_heads = max(1, channels // 64)
        self.head_dim = channels // self.n_heads
        from ..nn.layer.norm import LayerNorm

        self.norm1 = LayerNorm(channels)
        self.to_q1 = Linear(channels, channels, bias_attr=False)
        self.to_k1 = Linear(channels, channels, bias_attr=False)
        self.to_v1 = Linear(channels, channels, bias_attr=False)
        self.to_out1 = Linear(channels, channels)
        self.norm2 = LayerNorm(channels)
        self.to_q2 = Linear(channels, channels, bias_attr=False)
        self.to_k2 = Linear(ctx_dim, channels, bias_attr=False)
        self.to_v2 = Linear(ctx_dim, channels, bias_attr=False)
        self.to_out2 = Linear(channels, channels)
        self.norm3 = LayerNorm(channels)
        self.ff1 = Linear(channels, channels * 8)
        self.ff2 = Linear(channels * 4, channels)
        self.proj_out = Linear(channels, channels)

    def _attn(self, q, k, v):
        b, sq, c = q.shape
        sk = k.shape[1]
        qh = q.reshape(b, sq, self.n_heads, self.head_dim)
        kh = k.reshape(b, sk, self.n_heads, self.head_dim)
        vh = v.reshape(b, sk, self.n_heads, self.head_dim)
        out = F.scaled_dot_product_attention(qh, kh, vh, training=self.training)
        return out.reshape(b, sq, c)

    def forward(self, x, context):
        cl = layout.active()
        if cl:
            b, hh, ww, c = x.shape
            # channels-last: spatial→token flatten is a pure reshape
            h = self.norm(x).reshape(b, hh * ww, c)
        else:
            b, c, hh, ww = x.shape
            h = self.norm(x).reshape(b, c, hh * ww).transpose(0, 2, 1)
        residual_spatial = x
        h = self.proj_in(h)
        # self attention
        hn = self.norm1(h)
        h = h + self.to_out1(
            self._attn(self.to_q1(hn), self.to_k1(hn), self.to_v1(hn))
        )
        # cross attention
        hn = self.norm2(h)
        h = h + self.to_out2(
            self._attn(self.to_q2(hn), self.to_k2(context),
                       self.to_v2(context))
        )
        # GEGLU feed-forward
        hn = self.norm3(h)
        a, gate = jnp.split(self.ff1(hn), 2, axis=-1)
        h = h + self.ff2(a * F.gelu(gate))
        h = self.proj_out(h)
        h = h.reshape(b, hh, ww, c) if cl \
            else h.transpose(0, 2, 1).reshape(b, c, hh, ww)
        return residual_spatial + h


class Downsample(Layer):
    def __init__(self, channels):
        super().__init__()
        self.conv = Conv2D(channels, channels, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class UpsampleBlock(Layer):
    def __init__(self, channels):
        super().__init__()
        self.up = Upsample(scale_factor=2, mode="nearest")
        self.conv = Conv2D(channels, channels, 3, padding=1)

    def forward(self, x):
        return self.conv(self.up(x))


class UNet2DConditionModel(Layer):
    def __init__(self, config: UNetConfig):
        super().__init__()
        from ..nn.layer.common import LayerList

        self.config = config
        ch = config.block_out_channels
        temb_c = ch[0] * 4
        self.time_proj_dim = ch[0]
        self.time_embedding1 = Linear(ch[0], temb_c)
        self.time_embedding2 = Linear(temb_c, temb_c)
        self.conv_in = Conv2D(config.in_channels, ch[0], 3, padding=1)

        self.down_resnets = LayerList()
        self.down_attns = LayerList()
        self.downsamplers = LayerList()
        skip_channels = [ch[0]]
        cur = ch[0]
        for level, out_c in enumerate(ch):
            for _ in range(config.layers_per_block):
                self.down_resnets.append(
                    ResnetBlock(cur, out_c, temb_c, config.norm_num_groups)
                )
                use_attn = level >= len(ch) - 2
                self.down_attns.append(
                    CrossAttnBlock(out_c, config.cross_attention_dim,
                                   config.attention_head_dim,
                                   config.norm_num_groups)
                    if use_attn else None
                )
                cur = out_c
                skip_channels.append(cur)
            if level < len(ch) - 1:
                self.downsamplers.append(Downsample(cur))
                skip_channels.append(cur)

        self.mid_res1 = ResnetBlock(cur, cur, temb_c, config.norm_num_groups)
        self.mid_attn = CrossAttnBlock(
            cur, config.cross_attention_dim, config.attention_head_dim,
            config.norm_num_groups,
        )
        self.mid_res2 = ResnetBlock(cur, cur, temb_c, config.norm_num_groups)

        self.up_resnets = LayerList()
        self.up_attns = LayerList()
        self.upsamplers = LayerList()
        for level, out_c in enumerate(reversed(ch)):
            for _ in range(config.layers_per_block + 1):
                skip = skip_channels.pop()
                self.up_resnets.append(
                    ResnetBlock(cur + skip, out_c, temb_c,
                                config.norm_num_groups)
                )
                use_attn = level < 2
                self.up_attns.append(
                    CrossAttnBlock(out_c, config.cross_attention_dim,
                                   config.attention_head_dim,
                                   config.norm_num_groups)
                    if use_attn else None
                )
                cur = out_c
            if level < len(ch) - 1:
                self.upsamplers.append(UpsampleBlock(cur))

        self.conv_norm_out = GroupNorm(config.norm_num_groups, cur,
                                       activation="silu")
        self.conv_out = Conv2D(cur, config.out_channels, 3, padding=1)

    def forward(self, sample, timestep, encoder_hidden_states):
        """sample [b, c, h, w]; timestep [b]; context [b, s, ctx_dim]."""
        # the sinusoidal table is fp32 for accuracy; cast to the compute
        # dtype before it meets activations, or one add would silently
        # promote every downstream conv to fp32 under bf16 training
        temb = timestep_embedding(timestep, self.time_proj_dim)
        temb = temb.astype(self.time_embedding1.weight.value.dtype)
        temb = self.time_embedding2(F.silu(self.time_embedding1(temb)))

        cl = layout.decide(self.config.channels_last)
        if cl:
            # the ONLY layout transposes in the program: NCHW boundary →
            # NHWC body here, and back at the return
            sample = layout.nchw_to_nhwc(sample)
        cat_axis = -1 if cl else 1
        with layout.channels_last_scope(cl):
            h = self.conv_in(sample)
            skips = [h]
            cfg = self.config
            ri, di = 0, 0
            for level in range(len(cfg.block_out_channels)):
                for _ in range(cfg.layers_per_block):
                    h = self.down_resnets[ri](h, temb)
                    attn = self.down_attns[ri]
                    if attn is not None:
                        h = attn(h, encoder_hidden_states)
                    ri += 1
                    skips.append(h)
                if level < len(cfg.block_out_channels) - 1:
                    h = self.downsamplers[di](h)
                    di += 1
                    skips.append(h)

            h = self.mid_res1(h, temb)
            h = self.mid_attn(h, encoder_hidden_states)
            h = self.mid_res2(h, temb)

            ri, ui = 0, 0
            for level in range(len(cfg.block_out_channels)):
                for _ in range(cfg.layers_per_block + 1):
                    skip = skips.pop()
                    h = jnp.concatenate([h, skip], axis=cat_axis)
                    h = self.up_resnets[ri](h, temb)
                    attn = self.up_attns[ri]
                    if attn is not None:
                        h = attn(h, encoder_hidden_states)
                    ri += 1
                if level < len(cfg.block_out_channels) - 1:
                    h = self.upsamplers[ui](h)
                    ui += 1

            out = self.conv_out(self.conv_norm_out(h))
        return layout.nhwc_to_nchw(out) if cl else out
