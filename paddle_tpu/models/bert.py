"""BERT / ERNIE dense encoder family.

Parity: PaddleNLP's `BertModel`/`ErnieModel` stack (transformers/
bert/modeling.py, ernie/modeling.py) — the bidirectional encoder with
token/position/segment embeddings, post-LN transformer blocks, a pooler,
and the task heads paddle users reach for first:
``BertForSequenceClassification``, ``BertForMaskedLM`` (ERNIE shares the
same skeleton; its differences are pretraining data/objectives, not
architecture — construct with ``BertConfig(type_vocab_size=...,
act="relu")`` style knobs for the ERNIE variants).

TPU-native notes: bidirectional attention means no causal mask — the
flash kernel runs with causal=False and the whole [b, s, h] block is one
MXU-friendly program; attention_mask (padding) lowers to the flash
kernel's segment-id path, which skips fully-masked blocks instead of
materializing [b, s, s] additive masks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..distributed.parallel_layers.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..kernels import flash_attention as fa
from ..nn import functional as F
from ..nn.layer.common import Dropout, Embedding, LayerList, Linear
from ..nn.layer.norm import LayerNorm


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    num_labels: int = 2

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("hidden_dropout_prob", 0.0)
        kw.setdefault("attention_probs_dropout_prob", 0.0)
        return cls(**kw)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=init)
        self.token_type_embeddings = Embedding(
            config.type_vocab_size, config.hidden_size, weight_attr=init)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        if token_type_ids is None:
            token_type_ids = jnp.zeros((b, s), jnp.int32)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(h))


class BertSelfAttention(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, weight_attr=init)
        self.out_proj = RowParallelLinear(h, h, weight_attr=init)

    def forward(self, x, attention_mask=None):
        cfg = self.config
        b, s, _ = x.shape
        qkv = self.qkv_proj(x).reshape(
            b, s, 3, cfg.num_attention_heads, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        segment_ids = None
        if attention_mask is not None:
            # padding mask [b, s] (1 = real token) → flash segment ids:
            # padding becomes a sentinel segment nothing attends across
            segment_ids = jnp.where(attention_mask > 0, 0, 1).astype(
                jnp.int32)
        drop = cfg.attention_probs_dropout_prob if self.training else 0.0
        if cfg.use_flash_attention and drop == 0.0:
            out = fa.flash_attention(q, k, v, causal=False,
                                     segment_ids=segment_ids,
                                     training=self.training)
        else:
            mask = None
            if attention_mask is not None:
                mask = (attention_mask[:, None, None, :] > 0)
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=mask, dropout_p=drop,
                training=self.training)
        return self.out_proj(out.reshape(b, s, cfg.hidden_size))


class BertLayer(Layer):
    """Post-LN encoder block (the BERT/ERNIE original)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.attention = BertSelfAttention(config)
        self.attn_norm = LayerNorm(config.hidden_size,
                                   config.layer_norm_eps)
        self.fc_in = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, weight_attr=init)
        self.fc_out = RowParallelLinear(
            config.intermediate_size, config.hidden_size, weight_attr=init)
        self.ffn_norm = LayerNorm(config.hidden_size,
                                  config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        x = self.attn_norm(
            x + self.dropout(self.attention(x, attention_mask)))
        h = self.fc_out(F.gelu(self.fc_in(x)))
        return self.ffn_norm(x + self.dropout(h))


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.dense = Linear(config.hidden_size, config.hidden_size,
                            weight_attr=init)

    def forward(self, hidden):
        return jnp.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            h = layer(h, attention_mask)
        return h, self.pooler(h)


class BertForSequenceClassification(Layer):
    """Parity: paddlenlp BertForSequenceClassification — pooled [CLS]
    → dropout → linear; returns loss when labels given."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(
            config.hidden_size, config.num_labels,
            weight_attr=I.Normal(0.0, config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return F.cross_entropy(logits, labels)


class BertForMaskedLM(Layer):
    """Parity: paddlenlp BertForMaskedLM — transform + tied decoder over
    the word-embedding matrix; ignore_index=-100 masked loss."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        init = I.Normal(0.0, config.initializer_range)
        self.transform = Linear(config.hidden_size, config.hidden_size,
                                weight_attr=init)
        self.transform_norm = LayerNorm(config.hidden_size,
                                        config.layer_norm_eps)
        self.decoder_bias = self.create_parameter(
            (config.vocab_size,), is_bias=True)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        h, _ = self.bert(input_ids, token_type_ids,
                         attention_mask=attention_mask)
        h = self.transform_norm(F.gelu(self.transform(h)))
        w = self.bert.embeddings.word_embeddings.weight.value
        logits = h @ w.T + self.decoder_bias.value
        if labels is None:
            return logits
        return F.cross_entropy(
            logits.reshape(-1, self.config.vocab_size),
            labels.reshape(-1), ignore_index=-100)


# ERNIE is architecturally this encoder; provide the paddle-named surface
ErnieConfig = BertConfig
ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification
ErnieForMaskedLM = BertForMaskedLM
