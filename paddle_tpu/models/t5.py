"""T5 encoder-decoder family.

Parity: PaddleNLP `T5Model` / `T5ForConditionalGeneration`
(paddlenlp/transformers/t5/modeling.py) — the relative-position-bias
encoder-decoder with T5LayerNorm (RMS, no bias), no attention scaling
(folded into init), tied input embeddings, and the v1.1 gated-gelu MLP
variant behind ``feed_forward_proj``.

TPU-native notes: the relative position bias makes self-attention a
biased softmax, so it runs through the XLA SDPA path (additive bias
fuses into the logits einsum); cross-attention carries no bias and is
flash-eligible. The bias itself is computed ONCE per stack from a static
bucket table (host-free: jnp ops on broadcasted iotas) and reused by
every layer, exactly the reference's shared `relative_attention_bias`.
Decoding re-uses the encoder output; ``decode_step`` is a real
incremental path — per-layer self-attention KV caches plus cached
encoder cross-attention K/V, one token per step at fixed shapes
(``decode_step`` / ``_generate_cached`` below, tested in
tests/test_t5.py). Re-running the full prefix is never required.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..distributed.parallel_layers.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..kernels import flash_attention as fa
from ..nn import functional as F
from ..nn.layer.common import Dropout, LayerList


@dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: int = None
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    initializer_factor: float = 1.0
    feed_forward_proj: str = "relu"   # or "gated-gelu" (t5 v1.1)
    tie_word_embeddings: bool = True
    decoder_start_token_id: int = 0
    pad_token_id: int = 0
    use_flash_attention: bool = True

    def __post_init__(self):
        if self.num_decoder_layers is None:
            self.num_decoder_layers = self.num_layers

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("d_model", 64)
        kw.setdefault("d_kv", 16)
        kw.setdefault("d_ff", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("dropout_rate", 0.0)
        return cls(**kw)


class T5LayerNorm(Layer):
    """RMS norm, no bias, no mean subtraction (the T5 original)."""

    def __init__(self, hidden_size, eps=1e-6):
        super().__init__()
        from ..core.parameter import Parameter

        self.weight = Parameter(jnp.ones((hidden_size,)), name="t5ln_w")
        self.eps = eps

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.eps)


def _relative_position_bucket(relative_position, bidirectional, num_buckets,
                              max_distance):
    """Static bucket table (reference: T5Attention._relative_position_bucket)
    — pure jnp on iotas, shape [q, k] int32."""
    rp = relative_position
    ret = jnp.zeros_like(rp)
    if bidirectional:
        num_buckets //= 2
        ret = ret + jnp.where(rp > 0, num_buckets, 0)
        rp = jnp.abs(rp)
    else:
        rp = -jnp.minimum(rp, 0)
    max_exact = num_buckets // 2
    is_small = rp < max_exact
    log_ratio = (
        jnp.log(jnp.maximum(rp, 1).astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
    )
    large = max_exact + (log_ratio * (num_buckets - max_exact)).astype(
        jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, rp, large)


class T5RelativeBias(Layer):
    """The per-stack shared relative_attention_bias embedding."""

    def __init__(self, config: T5Config, bidirectional: bool):
        super().__init__()
        from ..nn.layer.common import Embedding

        self.embedding = Embedding(
            config.relative_attention_num_buckets, config.num_heads,
            weight_attr=I.Normal(
                0.0, config.initializer_factor * config.d_model ** -0.5),
        )
        self.bidirectional = bidirectional
        self.config = config

    def forward(self, q_len, k_len):
        cfg = self.config
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = _relative_position_bucket(
            mem - ctx, self.bidirectional,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance,
        )
        bias = self.embedding(buckets)            # [q, k, heads]
        return jnp.transpose(bias, (2, 0, 1))[None]  # [1, h, q, k]

    def row(self, q_pos, k_len):
        """Single-query bias row for cached decode: [1, h, 1, k_len].
        ``q_pos`` may be traced (the decode loop's cache index)."""
        cfg = self.config
        buckets = _relative_position_bucket(
            jnp.arange(k_len) - q_pos, self.bidirectional,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance,
        )
        bias = self.embedding(buckets)            # [k_len, heads]
        return jnp.transpose(bias, (1, 0))[None, :, None, :]


class T5Attention(Layer):
    def __init__(self, config: T5Config, is_cross: bool = False):
        super().__init__()
        cfg = config
        self.config = config
        self.is_cross = is_cross
        inner = cfg.num_heads * cfg.d_kv
        init = I.Normal(0.0, cfg.initializer_factor * (
            cfg.d_model * cfg.d_kv) ** -0.5)
        init_o = I.Normal(0.0, cfg.initializer_factor * inner ** -0.5)
        self.q = ColumnParallelLinear(cfg.d_model, inner, has_bias=False,
                                      weight_attr=init)
        self.k = ColumnParallelLinear(cfg.d_model, inner, has_bias=False,
                                      weight_attr=init)
        self.v = ColumnParallelLinear(cfg.d_model, inner, has_bias=False,
                                      weight_attr=init)
        self.o = RowParallelLinear(inner, cfg.d_model, has_bias=False,
                                   weight_attr=init_o)

    def project_kv(self, kv):
        """Project K/V once (cross-attention prefill: the encoder output
        never changes during decode)."""
        cfg = self.config
        b, sk, _ = kv.shape
        return (self.k(kv).reshape(b, sk, cfg.num_heads, cfg.d_kv),
                self.v(kv).reshape(b, sk, cfg.num_heads, cfg.d_kv))

    def decode_step(self, x, cache_index, kv_cache=None,
                    precomputed_kv=None, position_bias=None,
                    attention_mask=None):
        """Single-token attention against a cache. ``kv_cache``
        (k, v) [b, max_len, h, d] for self-attention (updated at
        ``cache_index``); ``precomputed_kv`` for cross-attention.
        Returns (out, new_kv_cache)."""
        cfg = self.config
        b = x.shape[0]
        q = self.q(x).reshape(b, 1, cfg.num_heads, cfg.d_kv)
        if precomputed_kv is not None:
            k, v = precomputed_kv
            bias = position_bias
            if attention_mask is not None:
                pad = jnp.where(attention_mask[:, None, None, :] > 0,
                                0.0, -1e30).astype(jnp.float32)
                bias = pad if bias is None else bias + pad
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=bias, scale=1.0, training=False)
            return self.o(out.reshape(b, 1, -1)), None
        ck, cv = kv_cache
        k_new = self.k(x).reshape(b, cfg.num_heads, cfg.d_kv)
        v_new = self.v(x).reshape(b, cfg.num_heads, cfg.d_kv)
        ck = jax.lax.dynamic_update_index_in_dim(
            ck, k_new[:, None], cache_index, 1)
        cv = jax.lax.dynamic_update_index_in_dim(
            cv, v_new[:, None], cache_index, 1)
        max_len = ck.shape[1]
        # causal validity: only positions <= cache_index are live
        live = jnp.arange(max_len) <= cache_index        # [max_len]
        bias = jnp.where(live, 0.0, -1e30)[None, None, None, :]
        if position_bias is not None:
            bias = bias + position_bias
        out = F.scaled_dot_product_attention(
            q, ck, cv, attn_mask=bias, scale=1.0, training=False)
        return self.o(out.reshape(b, 1, -1)), (ck, cv)

    def forward(self, x, kv=None, position_bias=None, causal=False,
                attention_mask=None):
        cfg = self.config
        b, sq, _ = x.shape
        kv = x if kv is None else kv
        sk = kv.shape[1]
        q = self.q(x).reshape(b, sq, cfg.num_heads, cfg.d_kv)
        k = self.k(kv).reshape(b, sk, cfg.num_heads, cfg.d_kv)
        v = self.v(kv).reshape(b, sk, cfg.num_heads, cfg.d_kv)
        drop = cfg.dropout_rate if self.training else 0.0
        if position_bias is None and cfg.use_flash_attention \
                and attention_mask is None and drop == 0.0:
            # cross-attention: bias-free → flash path (T5 convention:
            # no logit scaling, expressed via the kernel's scale arg)
            out = fa.flash_attention(
                q, k, v, causal=causal, scale=1.0,
                training=self.training)
        else:
            bias = position_bias
            if attention_mask is not None:
                pad = jnp.where(attention_mask[:, None, None, :] > 0,
                                0.0, -1e30).astype(jnp.float32)
                bias = pad if bias is None else bias + pad
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=bias, is_causal=causal, scale=1.0,
                dropout_p=drop, training=self.training)
        return self.o(out.reshape(b, sq, -1))


class T5FF(Layer):
    def __init__(self, config: T5Config):
        super().__init__()
        cfg = config
        init_i = I.Normal(0.0, cfg.initializer_factor * cfg.d_model ** -0.5)
        init_o = I.Normal(0.0, cfg.initializer_factor * cfg.d_ff ** -0.5)
        self.gated = cfg.feed_forward_proj.startswith("gated")
        self.wi = ColumnParallelLinear(cfg.d_model, cfg.d_ff,
                                       has_bias=False, weight_attr=init_i)
        if self.gated:
            self.wi_1 = ColumnParallelLinear(
                cfg.d_model, cfg.d_ff, has_bias=False, weight_attr=init_i)
        self.wo = RowParallelLinear(cfg.d_ff, cfg.d_model, has_bias=False,
                                    weight_attr=init_o)
        self.dropout = Dropout(cfg.dropout_rate)

    def forward(self, x):
        if self.gated:
            h = F.gelu(self.wi(x), approximate=True) * self.wi_1(x)
        else:
            h = F.relu(self.wi(x))
        return self.wo(self.dropout(h))


class T5Block(Layer):
    def __init__(self, config: T5Config, is_decoder: bool):
        super().__init__()
        self.is_decoder = is_decoder
        self.ln1 = T5LayerNorm(config.d_model, config.layer_norm_epsilon)
        self.self_attn = T5Attention(config)
        if is_decoder:
            self.ln_cross = T5LayerNorm(config.d_model,
                                        config.layer_norm_epsilon)
            self.cross_attn = T5Attention(config, is_cross=True)
        self.ln2 = T5LayerNorm(config.d_model, config.layer_norm_epsilon)
        self.ff = T5FF(config)
        self.dropout = Dropout(config.dropout_rate)

    def forward(self, x, enc=None, position_bias=None,
                attention_mask=None, enc_mask=None):
        # attention_mask here is THIS stack's padding mask (encoder's for
        # the encoder stack, decoder's for the decoder stack)
        x = x + self.dropout(self.self_attn(
            self.ln1(x), position_bias=position_bias,
            causal=self.is_decoder, attention_mask=attention_mask))
        if self.is_decoder and enc is not None:
            x = x + self.dropout(self.cross_attn(
                self.ln_cross(x), kv=enc, attention_mask=enc_mask))
        return x + self.dropout(self.ff(self.ln2(x)))

    def decode_step(self, x, cache_index, self_cache, cross_kv,
                    position_bias=None, enc_mask=None):
        """One cached decoder token. Returns (x, new_self_cache)."""
        h, self_cache = self.self_attn.decode_step(
            self.ln1(x), cache_index, kv_cache=self_cache,
            position_bias=position_bias)
        x = x + h
        h, _ = self.cross_attn.decode_step(
            self.ln_cross(x), cache_index, precomputed_kv=cross_kv,
            attention_mask=enc_mask)
        x = x + h
        return x + self.ff(self.ln2(x)), self_cache


class T5Stack(Layer):
    def __init__(self, config: T5Config, is_decoder: bool):
        super().__init__()
        n = config.num_decoder_layers if is_decoder else config.num_layers
        self.is_decoder = is_decoder
        self.relative_bias = T5RelativeBias(config,
                                            bidirectional=not is_decoder)
        self.blocks = LayerList(
            [T5Block(config, is_decoder) for _ in range(n)])
        self.final_norm = T5LayerNorm(config.d_model,
                                      config.layer_norm_epsilon)
        self.dropout = Dropout(config.dropout_rate)

    def forward(self, x, enc=None, attention_mask=None, enc_mask=None):
        s = x.shape[1]
        bias = self.relative_bias(s, s)   # shared by every block (parity)
        x = self.dropout(x)
        for blk in self.blocks:
            x = blk(x, enc=enc, position_bias=bias,
                    attention_mask=attention_mask, enc_mask=enc_mask)
        return self.dropout(self.final_norm(x))

    def init_decode(self, batch, max_len, enc, dtype=jnp.float32):
        """Decoder-only: allocate self-attention caches and project the
        cross-attention K/V once from the encoder output."""
        cfg = self.blocks[0].self_attn.config
        caches = [
            (jnp.zeros((batch, max_len, cfg.num_heads, cfg.d_kv), dtype),
             jnp.zeros((batch, max_len, cfg.num_heads, cfg.d_kv), dtype))
            for _ in self.blocks
        ]
        cross = [blk.cross_attn.project_kv(enc) for blk in self.blocks]
        return caches, cross

    def decode_step(self, x, cache_index, caches, cross_kvs,
                    enc_mask=None):
        """x: [b, 1, d_model] single-token embedding. Returns
        (hidden [b, 1, d], new_caches)."""
        max_len = caches[0][0].shape[1]
        bias = self.relative_bias.row(cache_index, max_len)
        new_caches = []
        for blk, cache, cross in zip(self.blocks, caches, cross_kvs):
            x, cache = blk.decode_step(
                x, cache_index, cache, cross, position_bias=bias,
                enc_mask=enc_mask)
            new_caches.append(cache)
        return self.final_norm(x), new_caches


class T5Model(Layer):
    def __init__(self, config: T5Config):
        super().__init__()
        self.config = config
        self.shared = VocabParallelEmbedding(
            config.vocab_size, config.d_model,
            weight_attr=I.Normal(0.0, config.initializer_factor),
        )
        self.encoder = T5Stack(config, is_decoder=False)
        self.decoder = T5Stack(config, is_decoder=True)

    def encode(self, input_ids, attention_mask=None):
        return self.encoder(self.shared(input_ids),
                            attention_mask=attention_mask)

    def decode(self, decoder_input_ids, enc, enc_mask=None,
               decoder_attention_mask=None):
        return self.decoder(self.shared(decoder_input_ids), enc=enc,
                            attention_mask=decoder_attention_mask,
                            enc_mask=enc_mask)

    def forward(self, input_ids, decoder_input_ids, attention_mask=None,
                decoder_attention_mask=None):
        enc = self.encode(input_ids, attention_mask)
        return self.decode(decoder_input_ids, enc, enc_mask=attention_mask,
                           decoder_attention_mask=decoder_attention_mask)


class T5ForConditionalGeneration(Layer):
    """seq2seq LM head; loss when ``labels`` given (paddle convention:
    labels shifted right internally to build decoder inputs)."""

    def __init__(self, config: T5Config):
        super().__init__()
        self.config = config
        self.t5 = T5Model(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.d_model, config.vocab_size, has_bias=False,
                weight_attr=I.Normal(0.0, config.initializer_factor),
            )

    def _shift_right(self, labels):
        start = jnp.full(
            (labels.shape[0], 1), self.config.decoder_start_token_id,
            labels.dtype)
        return jnp.concatenate([start, labels[:, :-1]], axis=1)

    def _logits(self, hidden):
        cfg = self.config
        if cfg.tie_word_embeddings:
            # rescale per the reference (d_model**-0.5 before the tied proj)
            hidden = hidden * (cfg.d_model ** -0.5)
            return hidden @ self.t5.shared.weight.value.T
        return self.lm_head(hidden)

    def forward(self, input_ids, decoder_input_ids=None, labels=None,
                attention_mask=None, decoder_attention_mask=None):
        if decoder_input_ids is None:
            if labels is None:
                raise ValueError("need decoder_input_ids or labels")
            decoder_input_ids = self._shift_right(labels)
        hidden = self.t5(input_ids, decoder_input_ids,
                         attention_mask=attention_mask,
                         decoder_attention_mask=decoder_attention_mask)
        logits = self._logits(hidden)
        if labels is None:
            return logits
        return F.cross_entropy(
            logits.reshape(-1, self.config.vocab_size), labels.reshape(-1),
            ignore_index=self.config.pad_token_id,
        )

    def generate(self, input_ids, max_length=20, attention_mask=None,
                 use_cache=True):
        """Greedy decode, encoder run once. ``use_cache=True`` (default)
        decodes incrementally — per-layer self-attention KV caches plus
        cross-attention K/V projected a single time from the encoder
        output, O(T) attention per new token. ``use_cache=False`` is the
        cache-free reference path (full decoder re-run inside a
        lax.scan), kept as the numerics oracle."""
        if use_cache:
            return self._generate_cached(input_ids, max_length,
                                         attention_mask)
        cfg = self.config
        enc = self.t5.encode(input_ids, attention_mask)
        b = input_ids.shape[0]
        buf = jnp.full((b, max_length), cfg.pad_token_id, jnp.int32)
        buf = buf.at[:, 0].set(cfg.decoder_start_token_id)

        def step(buf, t):
            hidden = self.t5.decode(buf, enc, enc_mask=attention_mask)
            logits = self._logits(hidden)          # [b, max_len, vocab]
            nxt = jnp.argmax(logits[:, t], axis=-1).astype(jnp.int32)
            # t ranges 0..max_length-2, so t+1 stays in bounds; the causal
            # mask keeps the pad suffix from influencing position t
            return buf.at[:, t + 1].set(nxt), nxt

        buf, toks = jax.lax.scan(step, buf, jnp.arange(max_length - 1))
        return buf

    def _generate_cached(self, input_ids, max_length, attention_mask):
        cfg = self.config
        enc = self.t5.encode(input_ids, attention_mask)
        b = input_ids.shape[0]
        caches, cross = self.t5.decoder.init_decode(
            b, max_length, enc, dtype=enc.dtype)
        buf = jnp.full((b, max_length), cfg.pad_token_id, jnp.int32)
        buf = buf.at[:, 0].set(cfg.decoder_start_token_id)

        def step(carry, t):
            buf, caches = carry
            tok = jax.lax.dynamic_slice_in_dim(buf, t, 1, axis=1)
            x = self.t5.shared(tok)                # [b, 1, d]
            hidden, caches = self.t5.decoder.decode_step(
                x, t, caches, cross, enc_mask=attention_mask)
            logits = self._logits(hidden)[:, 0]    # [b, vocab]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None], t + 1, axis=1)
            return (buf, caches), nxt

        (buf, _), _ = jax.lax.scan(
            step, (buf, caches), jnp.arange(max_length - 1))
        return buf
