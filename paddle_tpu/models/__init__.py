from .bert import (  # noqa: F401
    BertConfig,
    BertForMaskedLM,
    BertForSequenceClassification,
    BertModel,
    ErnieConfig,
    ErnieForMaskedLM,
    ErnieForSequenceClassification,
    ErnieModel,
)
from .ernie_moe import ErnieMoEConfig, ErnieMoEForCausalLM  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
)
from .mamba import MambaConfig, MambaForCausalLM  # noqa: F401
from .rwkv import RWKVConfig, RWKVForCausalLM  # noqa: F401
from .t5 import (  # noqa: F401
    T5Config,
    T5ForConditionalGeneration,
    T5Model,
)
from .unet import UNet2DConditionModel, UNetConfig  # noqa: F401
from .vit import ViT, ViTConfig  # noqa: F401
