from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
)
