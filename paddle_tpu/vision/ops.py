"""Detection ops (parity: python/paddle/vision/ops.py — nms, roi_align,
roi_pool, deform_conv2d, box_coder, prior_box; reference kernels in
paddle/phi/kernels/gpu/{nms,roi_align,roi_pool,deformable_conv}_kernel.cu).

TPU-native designs:
- nms: the O(n²) IoU matrix is one fused device program; the greedy
  suppression pass is a ``lax.fori_loop`` over a boolean keep-mask
  (static [n] shapes), with only the final dynamic-size index compaction
  on host — same split the reference uses (device IoU, host gather).
- roi_align: bilinear sampling as a dense gather (vmap over ROIs);
  every bin samples a static ``sampling_ratio²`` grid so the whole op is
  one jittable program, no atomics (the CUDA kernel's atomicAdd backward
  becomes plain autodiff through the gather).
- deform_conv2d: sampling locations = base grid + learned offsets;
  bilinear-sample all k·k taps (a gather), then the conv reduces to one
  einsum over [taps × in-channels] — MXU-shaped, autodiff-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


# ---------------------------------------------------------------------------
# IoU + NMS
# ---------------------------------------------------------------------------
def _box_iou_matrix(boxes_a, boxes_b):
    """IoU matrix [A, B]; boxes are [x1, y1, x2, y2]."""
    area_a = jnp.maximum(boxes_a[:, 2] - boxes_a[:, 0], 0) * \
        jnp.maximum(boxes_a[:, 3] - boxes_a[:, 1], 0)
    area_b = jnp.maximum(boxes_b[:, 2] - boxes_b[:, 0], 0) * \
        jnp.maximum(boxes_b[:, 3] - boxes_b[:, 1], 0)
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def _nms_keep_mask(boxes_sorted, iou_threshold):
    """Greedy NMS keep-mask over score-sorted boxes (jittable)."""
    n = boxes_sorted.shape[0]
    iou = _box_iou_matrix(boxes_sorted, boxes_sorted)
    idx = jnp.arange(n)

    def body(i, keep):
        # if box i survives, suppress every later box overlapping it
        suppress = (iou[i] > iou_threshold) & (idx > i)
        new_keep = keep & ~suppress
        return jnp.where(keep[i], new_keep, keep)

    return lax.fori_loop(0, n, body, jnp.ones((n,), bool))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Parity: paddle.vision.ops.nms. Returns kept indices into ``boxes``
    sorted by descending score. Dynamic-size output → eager op (the
    jittable core is ``_nms_keep_mask``)."""
    boxes = jnp.asarray(boxes)
    n = boxes.shape[0]
    if scores is None:
        scores = jnp.arange(n, 0, -1).astype(jnp.float32)
    scores = jnp.asarray(scores)
    if category_idxs is not None:
        # per-category NMS via the coordinate-offset trick: shift each
        # category by the full coordinate SPAN so the regions stay
        # disjoint wherever the frame sits (negative coords included)
        span = jnp.max(boxes) - jnp.min(boxes) + 1.0
        offs = jnp.asarray(category_idxs).astype(boxes.dtype) * span
        boxes = boxes + offs[:, None]
    order = jnp.argsort(-scores)
    keep_sorted = _nms_keep_mask(boxes[order], iou_threshold)
    kept = np.asarray(order)[np.asarray(keep_sorted)]
    if top_k is not None:
        kept = kept[:top_k]
    return jnp.asarray(kept)


# ---------------------------------------------------------------------------
# RoI align / pool
# ---------------------------------------------------------------------------
def _bilinear_sample(feat, y, x):
    """feat [C, H, W]; y/x arbitrary same-shaped grids → [C, *grid].
    Border-clamped wrapper over the shared 4-tap gather
    (nn.functional._bilerp)."""
    from ..nn.functional import _bilerp

    H, W = feat.shape[-2:]
    return _bilerp(feat, jnp.clip(y, 0.0, H - 1.0),
                   jnp.clip(x, 0.0, W - 1.0))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """Parity: paddle.vision.ops.roi_align. x: [N, C, H, W]; boxes
    [K, 4] in input-image coords; boxes_num [N] gives each image's ROI
    count (boxes are listed image-major).

    ``sampling_ratio=-1`` follows the reference's PER-ROI adaptive rule
    ``ceil(roi/output)`` exactly: the grid is statically sized to the
    batch max ratio R (XLA static shapes), each ROI computes its own
    sample positions from its own ratio, and padding slots are masked
    out of the bin average — bit-matching reference bin averaging for
    mixed-size batches. R caps at 16 (typical FPN ratios are 1-4);
    under tracing, where the batch max is unknowable, R falls back to
    4. Pass an explicit ``sampling_ratio`` to pin the grid."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    boxes_num = np.asarray(boxes_num)
    adaptive = sampling_ratio <= 0
    if not adaptive:
        R = int(sampling_ratio)
    else:
        # static grid size = batch max of the per-ROI adaptive ratios
        # (concrete/eager boxes); per-ROI masking below keeps numerics
        # exact for every ROI whose ratio fits
        try:
            bnp = np.asarray(boxes)
            sizes = np.maximum(bnp[:, 2:] - bnp[:, :2], 1.0) * spatial_scale
            R = int(min(16, max(
                1,
                np.ceil(sizes[:, 1].max() / ph).max(),
                np.ceil(sizes[:, 0].max() / pw).max(),
            )))
        except Exception:
            R = 4
    off = 0.5 if aligned else 0.0

    def one_roi(feat, box):
        x1, y1, x2, y2 = (box * spatial_scale) - off
        rw = jnp.maximum(x2 - x1, 1e-4 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-4 if aligned else 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        if adaptive:  # this ROI's own ceil(roi/output), clipped to R
            ry = jnp.clip(jnp.ceil(rh / ph), 1, R)
            rx = jnp.clip(jnp.ceil(rw / pw), 1, R)
        else:
            ry = rx = jnp.float32(R)
        j = jnp.arange(R, dtype=jnp.float32)
        # sample grid [ph, R] x [pw, R]; slots j >= r are masked padding
        iy = (jnp.arange(ph)[:, None] * bin_h + y1
              + (j[None, :] + 0.5) * bin_h / ry)
        ix = (jnp.arange(pw)[:, None] * bin_w + x1
              + (j[None, :] + 0.5) * bin_w / rx)
        yy = jnp.broadcast_to(iy[:, :, None, None], (ph, R, pw, R))
        xx = jnp.broadcast_to(ix[None, None, :, :], (ph, R, pw, R))
        vals = _bilinear_sample(feat, yy, xx)     # [C, ph, R, pw, R]
        w = ((j[:, None] < ry) & (j[None, :] < rx)).astype(vals.dtype)
        return (vals * w[None, None, :, None, :]).sum(axis=(2, 4)) \
            / (ry * rx)                            # [C, ph, pw]

    img_idx = np.repeat(np.arange(len(boxes_num)), boxes_num)
    feats = x[jnp.asarray(img_idx)]               # [K, C, H, W]
    return jax.vmap(one_roi)(feats, boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Parity: paddle.vision.ops.roi_pool (quantized max-pool bins)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    H, W = x.shape[-2:]
    boxes = jnp.asarray(boxes, jnp.float32)
    boxes_num = np.asarray(boxes_num)

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(feat, box):
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # bin b covers [start, end) with end >= start+1 (reference clamp)
        by0 = jnp.floor(y1 + jnp.arange(ph) * bin_h)
        by1 = jnp.ceil(y1 + (jnp.arange(ph) + 1) * bin_h)
        bx0 = jnp.floor(x1 + jnp.arange(pw) * bin_w)
        bx1 = jnp.ceil(x1 + (jnp.arange(pw) + 1) * bin_w)
        in_y = (ys[None, :] >= by0[:, None]) & (ys[None, :] < by1[:, None])
        in_x = (xs[None, :] >= bx0[:, None]) & (xs[None, :] < bx1[:, None])
        # [ph, pw, H, W] mask → max over the masked region per bin
        mask = in_y[:, None, :, None] & in_x[None, :, None, :]
        big_neg = jnp.asarray(-3.4e38, feat.dtype)
        masked = jnp.where(mask[None], feat[:, None, None], big_neg)
        out = masked.max(axis=(-1, -2))           # [C, ph, pw]
        empty = ~mask.any(axis=(-1, -2))
        return jnp.where(empty[None], 0.0, out)

    img_idx = np.repeat(np.arange(len(boxes_num)), boxes_num)
    feats = x[jnp.asarray(img_idx)]
    return jax.vmap(one_roi)(feats, boxes)


# ---------------------------------------------------------------------------
# box utilities
# ---------------------------------------------------------------------------
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True):
    """Parity: paddle.vision.ops.box_coder (SSD-style delta encode /
    decode)."""
    pb = jnp.asarray(prior_box, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    var = (jnp.asarray(prior_box_var, jnp.float32)
           if prior_box_var is not None else jnp.ones((4,)))
    norm = 0.0 if box_normalized else 1.0
    pw = pb[..., 2] - pb[..., 0] + norm
    ph = pb[..., 3] - pb[..., 1] + norm
    pcx = pb[..., 0] + 0.5 * pw
    pcy = pb[..., 1] + 0.5 * ph
    if code_type == "encode_center_size":
        tw = tb[..., 2] - tb[..., 0] + norm
        th = tb[..., 3] - tb[..., 1] + norm
        tcx = tb[..., 0] + 0.5 * tw
        tcy = tb[..., 1] + 0.5 * th
        dx = (tcx - pcx) / pw / var[..., 0]
        dy = (tcy - pcy) / ph / var[..., 1]
        dw = jnp.log(tw / pw) / var[..., 2]
        dh = jnp.log(th / ph) / var[..., 3]
        return jnp.stack([dx, dy, dw, dh], axis=-1)
    # decode_center_size
    dcx = var[..., 0] * tb[..., 0] * pw + pcx
    dcy = var[..., 1] * tb[..., 1] * ph + pcy
    dw = jnp.exp(var[..., 2] * tb[..., 2]) * pw
    dh = jnp.exp(var[..., 3] * tb[..., 3]) * ph
    return jnp.stack([
        dcx - 0.5 * dw, dcy - 0.5 * dh,
        dcx + 0.5 * dw - norm, dcy + 0.5 * dh - norm,
    ], axis=-1)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    """Parity: paddle.vision.ops.prior_box (SSD anchors). input
    [N, C, H, W] feature map; image [N, C, Him, Wim]."""
    H, W = input.shape[-2:]
    img_h, img_w = image.shape[-2:]
    step_h = steps[1] or img_h / H
    step_w = steps[0] or img_w / W
    ars = [1.0]
    for ar in aspect_ratios:
        if ar != 1.0:
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    sizes = []
    for i, ms in enumerate(min_sizes):
        for ar in ars:
            sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            bs = np.sqrt(ms * max_sizes[i])
            sizes.append((bs, bs))
    sizes = np.asarray(sizes, np.float32)       # [A, 2] (w, h)
    cx = (np.arange(W) + offset) * step_w
    cy = (np.arange(H) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)               # [H, W]
    boxes = np.stack([
        (cxg[..., None] - sizes[None, None, :, 0] / 2) / img_w,
        (cyg[..., None] - sizes[None, None, :, 1] / 2) / img_h,
        (cxg[..., None] + sizes[None, None, :, 0] / 2) / img_w,
        (cyg[..., None] + sizes[None, None, :, 1] / 2) / img_h,
    ], axis=-1)                                  # [H, W, A, 4]
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(
        np.asarray(variance, np.float32), boxes.shape).copy()
    return jnp.asarray(boxes), jnp.asarray(var)


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Parity: paddle.vision.ops.deform_conv2d (v1; v2/modulated when
    ``mask`` given). x [N, Cin, H, W]; offset
    [N, 2·dg·kh·kw, Hout, Wout] (paddle layout: per-tap (dy, dx) pairs);
    weight [Cout, Cin/groups, kh, kw]."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    x = jnp.asarray(x)
    N, Cin, H, W = x.shape
    Cout, cpg, kh, kw = weight.shape
    oh = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) // stride[0] + 1
    ow = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) // stride[1] + 1
    K = kh * kw
    dg = deformable_groups

    xp = jnp.pad(x, ((0, 0), (0, 0), (padding[0], padding[0]),
                     (padding[1], padding[1])))
    base_y = (jnp.arange(oh) * stride[0])[:, None, None] \
        + (jnp.arange(kh) * dilation[0])[None, None, :]
    base_x = (jnp.arange(ow) * stride[1])[:, None, None] \
        + (jnp.arange(kw) * dilation[1])[None, None, :]
    # offset layout [N, dg*K*2, oh, ow] → [N, dg, K, 2, oh, ow]
    off = offset.reshape(N, dg, K, 2, oh, ow)

    def per_image(feat, off_i, mask_i):
        # feat [Cin, Hp, Wp]; off_i [dg, K, 2, oh, ow]; mask_i
        # [dg, K, oh, ow] (all-ones when the caller gave no mask)
        cpdg = Cin // dg

        def per_dg(feat_g, off_g, mask_g):
            # off_g [K, 2, oh, ow] → per-tap sampling grids
            dy = off_g[:, 0]                      # [K, oh, ow]
            dx = off_g[:, 1]
            k_y = base_y.reshape(oh, 1, kh, 1)    # broadcast helpers
            k_x = base_x.reshape(1, ow, 1, kw)
            yy = (jnp.broadcast_to(k_y, (oh, ow, kh, kw))
                  .transpose(2, 3, 0, 1).reshape(K, oh, ow) + dy)
            xx = (jnp.broadcast_to(k_x, (oh, ow, kh, kw))
                  .transpose(2, 3, 0, 1).reshape(K, oh, ow) + dx)
            # reference semantics: taps OUTSIDE the (padded) map read 0,
            # not the clamped edge — a one-pixel zero ring + coordinate
            # shift makes the clamping _bilinear_sample produce exactly
            # that (far-out coords land wholly in the ring)
            ring = jnp.pad(feat_g, ((0, 0), (1, 1), (1, 1)))
            far = (yy < -1.0) | (yy > feat_g.shape[-2] + 0.0) | \
                (xx < -1.0) | (xx > feat_g.shape[-1] + 0.0)
            vals = _bilinear_sample(ring, yy + 1.0, xx + 1.0)
            vals = jnp.where(far[None], 0.0, vals)  # [cpdg, K, oh, ow]
            return vals * mask_g[None]

        feat_gs = feat.reshape(dg, cpdg, *feat.shape[-2:])
        vals = jax.vmap(per_dg)(feat_gs, off_i, mask_i)
        return vals.reshape(Cin, K, oh, ow)

    if mask is not None:
        mask_r = jnp.asarray(mask).reshape(N, dg, K, oh, ow)
    else:
        mask_r = jnp.ones((N, dg, K, oh, ow), x.dtype)
    sampled = jax.vmap(per_image)(xp, off, mask_r)  # [N, Cin, K, oh, ow]

    w = weight.reshape(groups, Cout // groups, cpg, K)
    s = sampled.reshape(N, groups, cpg, K, oh, ow)
    out = jnp.einsum("gock,ngckhw->ngohw", w, s).reshape(N, Cout, oh, ow)
    if bias is not None:
        out = out + jnp.asarray(bias).reshape(1, -1, 1, 1)
    return out


def matrix_nms(boxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0):
    """Parity: paddle.vision.ops.matrix_nms (SOLOv2) — unlike greedy NMS
    this is a closed-form parallel decay: every box's score is multiplied
    by min_j decay(iou_ij) over higher-scored overlapping boxes. No
    sequential loop at all — a single [n, n] program, the NMS variant
    that actually fits the TPU. boxes [N, 4]; scores [N] (single class).
    Returns (decayed_scores, keep_indices sorted by decayed score)."""
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    # reference order: score_threshold prunes ORIGINAL scores before the
    # decay; only post_threshold applies to decayed scores
    valid = np.asarray(scores >= score_threshold)
    valid_idx = np.nonzero(valid)[0]
    if valid_idx.size == 0:
        return jnp.zeros_like(scores), jnp.asarray(np.zeros(0, np.int64))
    sub_scores = scores[jnp.asarray(valid_idx)]
    sub_boxes = boxes[jnp.asarray(valid_idx)]
    order = jnp.argsort(-sub_scores)
    if nms_top_k > 0:
        order = order[:nms_top_k]
    b = sub_boxes[order]
    s = sub_scores[order]
    n = b.shape[0]
    iou = _box_iou_matrix(b, b)
    upper = jnp.triu(jnp.ones((n, n), bool), k=1)   # j < i by score
    iou_ji = jnp.where(upper, iou, 0.0).T            # [i, j] j higher
    # max overlap each higher-scored box j itself suffered
    comp = jnp.max(jnp.where(upper, iou, 0.0), axis=0)  # per column j
    if use_gaussian:
        # reference decay: exp(sigma*(comp^2 - iou^2)) — sigma MULTIPLIES
        decay = jnp.exp(gaussian_sigma
                        * (comp[None, :] ** 2 - iou_ji ** 2))
    else:
        decay = (1.0 - iou_ji) / jnp.maximum(1.0 - comp[None, :], 1e-10)
    decay = jnp.where(iou_ji > 0, decay, 1.0)
    decay_factor = jnp.min(decay, axis=1)
    new_scores = s * decay_factor
    keep = new_scores >= post_threshold
    # eager compaction (dynamic size, like nms)
    kept_sorted = jnp.argsort(-new_scores)
    orig = valid_idx[np.asarray(order)]
    kept = orig[np.asarray(kept_sorted)][np.asarray(keep[kept_sorted])]
    if keep_top_k > 0:
        kept = kept[:keep_top_k]
    out_scores = jnp.zeros_like(scores).at[jnp.asarray(orig)].set(
        new_scores)
    return out_scores, jnp.asarray(kept)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """Parity: paddle.vision.ops.psroi_pool (R-FCN position-sensitive
    average pooling): input [N, C·ph·pw, H, W] → [K, C, ph, pw]; output
    bin (c, i, j) averages channel c·ph·pw + i·pw + j over the bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    Cin, H, W = x.shape[1], x.shape[2], x.shape[3]
    C = Cin // (ph * pw)
    boxes = jnp.asarray(boxes, jnp.float32)
    boxes_num = np.asarray(boxes_num)
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(feat, box):
        x1 = box[0] * spatial_scale
        y1 = box[1] * spatial_scale
        x2 = box[2] * spatial_scale
        y2 = box[3] * spatial_scale
        bin_h = jnp.maximum(y2 - y1, 0.1) / ph
        bin_w = jnp.maximum(x2 - x1, 0.1) / pw
        by0 = jnp.floor(y1 + jnp.arange(ph) * bin_h)
        by1 = jnp.ceil(y1 + (jnp.arange(ph) + 1) * bin_h)
        bx0 = jnp.floor(x1 + jnp.arange(pw) * bin_w)
        bx1 = jnp.ceil(x1 + (jnp.arange(pw) + 1) * bin_w)
        in_y = (ys[None, :] >= by0[:, None]) & (ys[None, :] < by1[:, None])
        in_x = (xs[None, :] >= bx0[:, None]) & (xs[None, :] < bx1[:, None])
        mask = in_y[:, None, :, None] & in_x[None, :, None, :]  # ph,pw,H,W
        # position-sensitive channel selection: [C, ph, pw, H, W]
        fs = feat.reshape(C, ph, pw, H, W)
        num = jnp.sum(jnp.where(mask[None], fs, 0.0), axis=(-1, -2))
        den = jnp.maximum(mask.sum(axis=(-1, -2)), 1)[None]
        return num / den                                  # [C, ph, pw]

    img_idx = np.repeat(np.arange(len(boxes_num)), boxes_num)
    feats = x[jnp.asarray(img_idx)]
    return jax.vmap(one_roi)(feats, boxes)
