"""Image transforms (parity: python/paddle/vision/transforms/ —
Compose/Resize/Crop/Flip/Normalize/ToTensor and the functional forms).

All transforms operate host-side on PIL Images or numpy HWC arrays —
preprocessing belongs on CPU, overlapped with device compute via the
DataLoader prefetcher, never inside the jitted step.
"""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

try:  # PIL is the image decode path, as in the reference
    from PIL import Image

    _HAS_PIL = True
except Exception:  # pragma: no cover
    _HAS_PIL = False


def _is_pil(img):
    return _HAS_PIL and isinstance(img, Image.Image)


def _size_pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


# ---------------------------------------------------------------- functional


def to_tensor(img, data_format="CHW"):
    """PIL/HWC-uint8 → float32 in [0,1], CHW (paddle default) or HWC."""
    if _is_pil(img):
        img = np.asarray(img)
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def normalize(img, mean, std, data_format="CHW"):
    arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    # int size = resize the SHORTER edge to `size`, preserving aspect
    # ratio (paddle semantics); (h, w) = exact target.
    if isinstance(size, numbers.Number):
        if _is_pil(img):
            iw, ih = img.size
        else:
            ih, iw = np.asarray(img).shape[:2]
        s = int(size)
        if ih <= iw:
            h, w = s, max(1, int(round(iw * s / ih)))
        else:
            h, w = max(1, int(round(ih * s / iw))), s
    else:
        h, w = _size_pair(size)
    if _is_pil(img):
        modes = {
            "nearest": Image.NEAREST,
            "bilinear": Image.BILINEAR,
            "bicubic": Image.BICUBIC,
        }
        return img.resize((w, h), modes.get(interpolation, Image.BILINEAR))
    # numpy path: nearest / bilinear via index interpolation
    arr = np.asarray(img)
    src_h, src_w = arr.shape[:2]
    if interpolation == "nearest":
        ys = np.clip(
            np.round(np.linspace(0, src_h - 1, h)).astype(int), 0, src_h - 1
        )
        xs = np.clip(
            np.round(np.linspace(0, src_w - 1, w)).astype(int), 0, src_w - 1
        )
        return arr[ys][:, xs]
    ys = np.linspace(0, src_h - 1, h)
    xs = np.linspace(0, src_w - 1, w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, src_h - 1)
    x1 = np.minimum(x0 + 1, src_w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = arr.astype(np.float32)
    top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
    bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def crop(img, top, left, height, width):
    if _is_pil(img):
        # PIL pads out-of-bounds crops with zeros; mirror that on the
        # numpy path below so both backends return the requested size
        return img.crop((left, top, left + width, top + height))
    arr = np.asarray(img)
    out = arr[max(top, 0): max(top + height, 0),
              max(left, 0): max(left + width, 0)]
    if out.shape[0] != height or out.shape[1] != width:
        padded = np.zeros((height, width) + arr.shape[2:], dtype=arr.dtype)
        oy = max(-top, 0)
        ox = max(-left, 0)
        padded[oy:oy + out.shape[0], ox:ox + out.shape[1]] = out
        return padded
    return out


def center_crop(img, size):
    h, w = _size_pair(size)
    if _is_pil(img):
        iw, ih = img.size
    else:
        ih, iw = np.asarray(img).shape[:2]
    return crop(img, max(0, (ih - h) // 2), max(0, (iw - w) // 2), h, w)


def hflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return np.asarray(img)[:, ::-1]


def vflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return np.asarray(img)[::-1]


# ------------------------------------------------------------------ classes


class BaseTransform:
    def __call__(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW"):
        # scalars broadcast over whatever channel count the image has
        # (a grayscale input must stay single-channel)
        if isinstance(mean, numbers.Number):
            mean = [mean]
        if isinstance(std, numbers.Number):
            std = [std]
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = _size_pair(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad, mode="constant")
        h, w = self.size
        ih, iw = arr.shape[:2]
        top = random.randint(0, max(0, ih - h))
        left = random.randint(0, max(0, iw - w))
        return crop(arr, top, left, h, w)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if random.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = _size_pair(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img) if not _is_pil(img) else img
        if _is_pil(arr):
            iw, ih = arr.size
        else:
            ih, iw = arr.shape[:2]
        area = ih * iw
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            w = int(round((target * ar) ** 0.5))
            h = int(round((target / ar) ** 0.5))
            if 0 < w <= iw and 0 < h <= ih:
                top = random.randint(0, ih - h)
                left = random.randint(0, iw - w)
                patch = crop(img, top, left, h, w)
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(img, min(ih, iw)), self.size,
                      self.interpolation)


class Transpose(BaseTransform):
    """HWC → CHW (paddle parity for pipelines that skip ToTensor)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


# ---------------------------------------------------------------------------
# geometric transforms over grid_sample (round 3: rotate/affine/perspective)
# ---------------------------------------------------------------------------
def _apply_inverse_matrix(img, inv3x3, interpolation="bilinear", fill=0.0):
    """Warp CHW/NCHW image by the INVERSE 3x3 pixel-coordinate matrix via
    one grid_sample call (zeros padding ≈ constant fill 0)."""
    import jax.numpy as jnp

    from ..nn.functional import grid_sample

    single = img.ndim == 3
    x = jnp.asarray(img)[None] if single else jnp.asarray(img)
    n, c, h, w = x.shape
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    ones = jnp.ones_like(xs)
    tgt = jnp.stack([xs, ys, ones], 0).reshape(3, -1)     # [3, H*W]
    src = jnp.asarray(inv3x3, jnp.float32) @ tgt           # [3, H*W]
    sx = src[0] / jnp.maximum(jnp.abs(src[2]), 1e-9) * jnp.sign(src[2])
    sy = src[1] / jnp.maximum(jnp.abs(src[2]), 1e-9) * jnp.sign(src[2])
    # pixel coords → normalized [-1, 1] (align_corners=False convention)
    gx = (2.0 * sx + 1.0) / w - 1.0
    gy = (2.0 * sy + 1.0) / h - 1.0
    grid = jnp.stack([gx, gy], -1).reshape(1, h, w, 2)
    grid = jnp.broadcast_to(grid, (n, h, w, 2))
    out = grid_sample(x, grid, mode=interpolation,
                      padding_mode="zeros", align_corners=False)
    if fill:
        # zeros padding filled the outside with 0; shift to `fill`
        mask = grid_sample(jnp.ones_like(x[:, :1]), grid,
                           mode=interpolation, padding_mode="zeros",
                           align_corners=False)
        out = out + (1.0 - mask) * fill
    return out[0] if single else out


def _affine_pixel_matrix(angle, translate, scale, shear, center):
    """Forward 2x3 affine in pixel coords (paddle/torchvision
    convention: rotate about center, then shear/scale/translate)."""
    import math

    cx, cy = center
    # positive angle = counter-clockwise in display coords (y down), the
    # paddle/torchvision convention
    rot = math.radians(-angle)
    sx, sy = [math.radians(s) for s in shear]
    # RSS = rotate ∘ shear ∘ scale (torchvision _get_inverse_affine_matrix)
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    m = [[scale * a, scale * b, 0.0], [scale * c, scale * d, 0.0]]
    tx, ty = translate
    m[0][2] = cx + tx - m[0][0] * cx - m[0][1] * cy
    m[1][2] = cy + ty - m[1][0] * cx - m[1][1] * cy
    return m


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0.0, center=None):
    """Parity: paddle.vision.transforms.functional.affine (CHW tensors)."""
    import numpy as np

    h, w = img.shape[-2:]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if not isinstance(shear, (tuple, list)):
        shear = (shear, 0.0)
    m = np.vstack([_affine_pixel_matrix(angle, translate, scale, shear,
                                        center), [0.0, 0.0, 1.0]])
    return _apply_inverse_matrix(img, np.linalg.inv(m), interpolation,
                                 fill)


def rotate(img, angle, interpolation="bilinear", expand=False, fill=0.0,
           center=None):
    """Parity: paddle.vision.transforms.functional.rotate (expand=False)."""
    if expand:
        raise NotImplementedError("rotate(expand=True) not supported")
    return affine(img, angle=angle, interpolation=interpolation,
                  fill=fill, center=center)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0.0):
    """Parity: paddle.vision.transforms.functional.perspective — warp so
    ``startpoints`` (4 [x, y] corners) map onto ``endpoints``."""
    import numpy as np

    a = []
    bvec = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        a.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        bvec += [ex, ey]
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(bvec, np.float64))
    m = np.append(coeffs, 1.0).reshape(3, 3)
    return _apply_inverse_matrix(img, np.linalg.inv(m), interpolation,
                                 fill)


def _symmetric_range(value):
    """scalar d → (-d, d); sequence → tuple(value)."""
    import numpy as np

    return (-value, value) if np.isscalar(value) else tuple(value)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="bilinear", fill=0.0,
                 center=None, seed=None):
        import numpy as np

        self.degrees = _symmetric_range(degrees)
        self.interpolation = interpolation
        self.fill = fill
        self.center = center
        self._rng = np.random.default_rng(seed)

    def __call__(self, img):
        ang = float(self._rng.uniform(*self.degrees))
        return rotate(img, ang, self.interpolation, fill=self.fill,
                      center=self.center)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0.0, seed=None):
        import numpy as np

        self.degrees = _symmetric_range(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = None if shear is None else _symmetric_range(shear)
        self.interpolation = interpolation
        self.fill = fill
        self._rng = np.random.default_rng(seed)

    def __call__(self, img):
        h, w = img.shape[-2:]
        ang = float(self._rng.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate is not None:
            tx = float(self._rng.uniform(-self.translate[0],
                                         self.translate[0]) * w)
            ty = float(self._rng.uniform(-self.translate[1],
                                         self.translate[1]) * h)
        sc = 1.0 if self.scale is None else float(
            self._rng.uniform(*self.scale))
        sh = (0.0, 0.0) if self.shear is None else (
            float(self._rng.uniform(*self.shear)), 0.0)
        return affine(img, ang, (tx, ty), sc, sh, self.interpolation,
                      self.fill)
