"""Image transforms (parity: python/paddle/vision/transforms/ —
Compose/Resize/Crop/Flip/Normalize/ToTensor and the functional forms).

All transforms operate host-side on PIL Images or numpy HWC arrays —
preprocessing belongs on CPU, overlapped with device compute via the
DataLoader prefetcher, never inside the jitted step.
"""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

try:  # PIL is the image decode path, as in the reference
    from PIL import Image

    _HAS_PIL = True
except Exception:  # pragma: no cover
    _HAS_PIL = False


def _is_pil(img):
    return _HAS_PIL and isinstance(img, Image.Image)


def _size_pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


# ---------------------------------------------------------------- functional


def to_tensor(img, data_format="CHW"):
    """PIL/HWC-uint8 → float32 in [0,1], CHW (paddle default) or HWC."""
    if _is_pil(img):
        img = np.asarray(img)
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def normalize(img, mean, std, data_format="CHW"):
    arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    # int size = resize the SHORTER edge to `size`, preserving aspect
    # ratio (paddle semantics); (h, w) = exact target.
    if isinstance(size, numbers.Number):
        if _is_pil(img):
            iw, ih = img.size
        else:
            ih, iw = np.asarray(img).shape[:2]
        s = int(size)
        if ih <= iw:
            h, w = s, max(1, int(round(iw * s / ih)))
        else:
            h, w = max(1, int(round(ih * s / iw))), s
    else:
        h, w = _size_pair(size)
    if _is_pil(img):
        modes = {
            "nearest": Image.NEAREST,
            "bilinear": Image.BILINEAR,
            "bicubic": Image.BICUBIC,
        }
        return img.resize((w, h), modes.get(interpolation, Image.BILINEAR))
    # numpy path: nearest / bilinear via index interpolation
    arr = np.asarray(img)
    src_h, src_w = arr.shape[:2]
    if interpolation == "nearest":
        ys = np.clip(
            np.round(np.linspace(0, src_h - 1, h)).astype(int), 0, src_h - 1
        )
        xs = np.clip(
            np.round(np.linspace(0, src_w - 1, w)).astype(int), 0, src_w - 1
        )
        return arr[ys][:, xs]
    ys = np.linspace(0, src_h - 1, h)
    xs = np.linspace(0, src_w - 1, w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, src_h - 1)
    x1 = np.minimum(x0 + 1, src_w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = arr.astype(np.float32)
    top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
    bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def crop(img, top, left, height, width):
    if _is_pil(img):
        # PIL pads out-of-bounds crops with zeros; mirror that on the
        # numpy path below so both backends return the requested size
        return img.crop((left, top, left + width, top + height))
    arr = np.asarray(img)
    out = arr[max(top, 0): max(top + height, 0),
              max(left, 0): max(left + width, 0)]
    if out.shape[0] != height or out.shape[1] != width:
        padded = np.zeros((height, width) + arr.shape[2:], dtype=arr.dtype)
        oy = max(-top, 0)
        ox = max(-left, 0)
        padded[oy:oy + out.shape[0], ox:ox + out.shape[1]] = out
        return padded
    return out


def center_crop(img, size):
    h, w = _size_pair(size)
    if _is_pil(img):
        iw, ih = img.size
    else:
        ih, iw = np.asarray(img).shape[:2]
    return crop(img, max(0, (ih - h) // 2), max(0, (iw - w) // 2), h, w)


def hflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return np.asarray(img)[:, ::-1]


def vflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return np.asarray(img)[::-1]


# ------------------------------------------------------------------ classes


class BaseTransform:
    def __call__(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW"):
        # scalars broadcast over whatever channel count the image has
        # (a grayscale input must stay single-channel)
        if isinstance(mean, numbers.Number):
            mean = [mean]
        if isinstance(std, numbers.Number):
            std = [std]
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = _size_pair(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad, mode="constant")
        h, w = self.size
        ih, iw = arr.shape[:2]
        top = random.randint(0, max(0, ih - h))
        left = random.randint(0, max(0, iw - w))
        return crop(arr, top, left, h, w)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if random.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = _size_pair(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img) if not _is_pil(img) else img
        if _is_pil(arr):
            iw, ih = arr.size
        else:
            ih, iw = arr.shape[:2]
        area = ih * iw
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            w = int(round((target * ar) ** 0.5))
            h = int(round((target / ar) ** 0.5))
            if 0 < w <= iw and 0 < h <= ih:
                top = random.randint(0, ih - h)
                left = random.randint(0, iw - w)
                patch = crop(img, top, left, h, w)
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(img, min(ih, iw)), self.size,
                      self.interpolation)


class Transpose(BaseTransform):
    """HWC → CHW (paddle parity for pipelines that skip ToTensor)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


# ---------------------------------------------------------------------------
# geometric transforms over grid_sample (round 3: rotate/affine/perspective)
# ---------------------------------------------------------------------------
def _apply_inverse_matrix(img, inv3x3, interpolation="bilinear", fill=0.0):
    """Warp CHW/NCHW image by the INVERSE 3x3 pixel-coordinate matrix via
    one grid_sample call (zeros padding ≈ constant fill 0)."""
    import jax.numpy as jnp

    from ..nn.functional import grid_sample

    single = img.ndim == 3
    x = jnp.asarray(img)[None] if single else jnp.asarray(img)
    n, c, h, w = x.shape
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    ones = jnp.ones_like(xs)
    tgt = jnp.stack([xs, ys, ones], 0).reshape(3, -1)     # [3, H*W]
    src = jnp.asarray(inv3x3, jnp.float32) @ tgt           # [3, H*W]
    sx = src[0] / jnp.maximum(jnp.abs(src[2]), 1e-9) * jnp.sign(src[2])
    sy = src[1] / jnp.maximum(jnp.abs(src[2]), 1e-9) * jnp.sign(src[2])
    # pixel coords → normalized [-1, 1] (align_corners=False convention)
    gx = (2.0 * sx + 1.0) / w - 1.0
    gy = (2.0 * sy + 1.0) / h - 1.0
    grid = jnp.stack([gx, gy], -1).reshape(1, h, w, 2)
    grid = jnp.broadcast_to(grid, (n, h, w, 2))
    out = grid_sample(x, grid, mode=interpolation,
                      padding_mode="zeros", align_corners=False)
    if fill:
        # zeros padding filled the outside with 0; shift to `fill`
        mask = grid_sample(jnp.ones_like(x[:, :1]), grid,
                           mode=interpolation, padding_mode="zeros",
                           align_corners=False)
        out = out + (1.0 - mask) * fill
    return out[0] if single else out


def _affine_pixel_matrix(angle, translate, scale, shear, center):
    """Forward 2x3 affine in pixel coords (paddle/torchvision
    convention: rotate about center, then shear/scale/translate)."""
    import math

    cx, cy = center
    # positive angle = counter-clockwise in display coords (y down), the
    # paddle/torchvision convention
    rot = math.radians(-angle)
    sx, sy = [math.radians(s) for s in shear]
    # RSS = rotate ∘ shear ∘ scale (torchvision _get_inverse_affine_matrix)
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    m = [[scale * a, scale * b, 0.0], [scale * c, scale * d, 0.0]]
    tx, ty = translate
    m[0][2] = cx + tx - m[0][0] * cx - m[0][1] * cy
    m[1][2] = cy + ty - m[1][0] * cx - m[1][1] * cy
    return m


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0.0, center=None):
    """Parity: paddle.vision.transforms.functional.affine (CHW tensors)."""
    import numpy as np

    h, w = img.shape[-2:]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if not isinstance(shear, (tuple, list)):
        shear = (shear, 0.0)
    m = np.vstack([_affine_pixel_matrix(angle, translate, scale, shear,
                                        center), [0.0, 0.0, 1.0]])
    return _apply_inverse_matrix(img, np.linalg.inv(m), interpolation,
                                 fill)


def rotate(img, angle, interpolation="bilinear", expand=False, fill=0.0,
           center=None):
    """Parity: paddle.vision.transforms.functional.rotate (expand=False)."""
    if expand:
        raise NotImplementedError("rotate(expand=True) not supported")
    return affine(img, angle=angle, interpolation=interpolation,
                  fill=fill, center=center)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0.0):
    """Parity: paddle.vision.transforms.functional.perspective — warp so
    ``startpoints`` (4 [x, y] corners) map onto ``endpoints``."""
    import numpy as np

    a = []
    bvec = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        a.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        bvec += [ex, ey]
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(bvec, np.float64))
    m = np.append(coeffs, 1.0).reshape(3, 3)
    return _apply_inverse_matrix(img, np.linalg.inv(m), interpolation,
                                 fill)


def _symmetric_range(value):
    """scalar d → (-d, d); sequence → tuple(value)."""
    import numpy as np

    return (-value, value) if np.isscalar(value) else tuple(value)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="bilinear", fill=0.0,
                 center=None, seed=None):
        import numpy as np

        self.degrees = _symmetric_range(degrees)
        self.interpolation = interpolation
        self.fill = fill
        self.center = center
        self._rng = np.random.default_rng(seed)

    def __call__(self, img):
        ang = float(self._rng.uniform(*self.degrees))
        return rotate(img, ang, self.interpolation, fill=self.fill,
                      center=self.center)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0.0, seed=None):
        import numpy as np

        self.degrees = _symmetric_range(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = None if shear is None else _symmetric_range(shear)
        self.interpolation = interpolation
        self.fill = fill
        self._rng = np.random.default_rng(seed)

    def __call__(self, img):
        h, w = img.shape[-2:]
        ang = float(self._rng.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate is not None:
            tx = float(self._rng.uniform(-self.translate[0],
                                         self.translate[0]) * w)
            ty = float(self._rng.uniform(-self.translate[1],
                                         self.translate[1]) * h)
        sc = 1.0 if self.scale is None else float(
            self._rng.uniform(*self.scale))
        sh = (0.0, 0.0) if self.shear is None else (
            float(self._rng.uniform(*self.shear)), 0.0)
        return affine(img, ang, (tx, ty), sc, sh, self.interpolation,
                      self.fill)


# ------------------------------------------------- color / photometric ops
def _to_hwc_float(img):
    """PIL/HWC array → (float32 HWC ndarray, was_pil, was_uint8)."""
    was_pil = _is_pil(img)
    arr = np.asarray(img)
    was_u8 = arr.dtype == np.uint8
    a = arr.astype(np.float32)
    return a, was_pil, was_u8


def _restore(a, was_pil, was_u8):
    if was_u8:
        a = np.clip(np.round(a), 0, 255).astype(np.uint8)
    if was_pil:
        return Image.fromarray(a)
    return a


def adjust_brightness(img, brightness_factor):
    """Parity: paddle adjust_brightness — img * factor (blend with
    black), torchvision math."""
    a, p, u = _to_hwc_float(img)
    return _restore(a * brightness_factor, p, u)


def _grayscale(a):
    if a.ndim == 2 or a.shape[-1] == 1:
        return a if a.ndim == 2 else a[..., 0]
    return (0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2])


def adjust_contrast(img, contrast_factor):
    a, p, u = _to_hwc_float(img)
    mean = _grayscale(a).mean()
    return _restore(mean + contrast_factor * (a - mean), p, u)


def adjust_saturation(img, saturation_factor):
    a, p, u = _to_hwc_float(img)
    gray = _grayscale(a)[..., None]
    return _restore(gray + saturation_factor * (a - gray), p, u)


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5]: shift hue in HSV space."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a, p, u = _to_hwc_float(img)
    scale = 255.0 if u else 1.0
    rgb = a / scale
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = rgb.max(-1)
    minc = rgb.min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    dd = np.maximum(d, 1e-12)
    rc, gc, bc = (maxc - r) / dd, (maxc - g) / dd, (maxc - b) / dd
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(d == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    pp = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, pp, pp, t, v])
    g2 = np.choose(i, [t, v, v, q, pp, pp])
    b2 = np.choose(i, [pp, pp, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1) * scale
    return _restore(out, p, u)


def to_grayscale(img, num_output_channels=1):
    a, p, u = _to_hwc_float(img)
    g = _grayscale(a)[..., None]
    out = np.repeat(g, num_output_channels, axis=-1)
    if p and num_output_channels == 1:
        out = out[..., 0]
    return _restore(out, p, u)


def pad(img, padding, fill=0, padding_mode="constant"):
    """Parity: paddle transforms.pad — padding int | (lr, tb) |
    (l, t, r, b); HWC arrays or PIL."""
    if isinstance(padding, numbers.Number):
        l = t = r = b = int(padding)
    elif len(padding) == 2:
        l = r = int(padding[0])
        t = b = int(padding[1])
    else:
        l, t, r, b = (int(v) for v in padding)
    was_pil = _is_pil(img)
    arr = np.asarray(img)
    width = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        out = np.pad(arr, width, constant_values=fill)
    else:
        out = np.pad(arr, width, mode=padding_mode)
    return Image.fromarray(out) if was_pil else out


def erase(img, i, j, h, w, v, inplace=False):
    """Parity: paddle transforms.erase — fill [i:i+h, j:j+w] with v.
    CHW tensors/arrays (or HWC with trailing channel)."""
    import jax.numpy as jnp

    if isinstance(img, np.ndarray):
        out = img if inplace else img.copy()
        if out.ndim == 3 and out.shape[0] in (1, 3):   # CHW
            out[:, i:i + h, j:j + w] = v
        else:
            out[i:i + h, j:j + w] = v
        return out
    x = img
    if x.ndim == 3 and x.shape[0] in (1, 3):
        return x.at[:, i:i + h, j:j + w].set(v)
    return x.at[i:i + h, j:j + w].set(v)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.args = (padding, fill, padding_mode)

    def __call__(self, img):
        return pad(img, *self.args)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.n)


class BrightnessTransform(BaseTransform):
    """value v: factor drawn U[max(0, 1-v), 1+v] (paddle semantics)."""

    def __init__(self, value, seed=None):
        self.value = value
        self._rng = np.random.default_rng(seed)

    def _factor(self):
        v = self.value
        return float(self._rng.uniform(max(0.0, 1 - v), 1 + v))

    def __call__(self, img):
        return adjust_brightness(img, self._factor())


class ContrastTransform(BrightnessTransform):
    def __call__(self, img):
        return adjust_contrast(img, self._factor())


class SaturationTransform(BrightnessTransform):
    def __call__(self, img):
        return adjust_saturation(img, self._factor())


class HueTransform(BaseTransform):
    """value v <= 0.5: shift drawn U[-v, v]."""

    def __init__(self, value, seed=None):
        self.value = value
        self._rng = np.random.default_rng(seed)

    def __call__(self, img):
        return adjust_hue(img, float(self._rng.uniform(-self.value,
                                                       self.value)))


class ColorJitter(BaseTransform):
    """Parity: paddle ColorJitter — brightness/contrast/saturation/hue
    jitter applied in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 seed=None):
        self._rng = np.random.default_rng(seed)
        self.ops = []
        if brightness:
            self.ops.append(BrightnessTransform(brightness,
                                                seed=self._rng.integers(2**31)))
        if contrast:
            self.ops.append(ContrastTransform(contrast,
                                              seed=self._rng.integers(2**31)))
        if saturation:
            self.ops.append(SaturationTransform(saturation,
                                                seed=self._rng.integers(2**31)))
        if hue:
            self.ops.append(HueTransform(hue,
                                         seed=self._rng.integers(2**31)))

    def __call__(self, img):
        for k in self._rng.permutation(len(self.ops)):
            img = self.ops[int(k)](img)
        return img


class RandomPerspective(BaseTransform):
    """Parity: paddle RandomPerspective — random corner displacement
    warp with probability ``prob``."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="bilinear", fill=0.0, seed=None):
        self.prob = prob
        self.scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill
        self._rng = np.random.default_rng(seed)

    def __call__(self, img):
        if self._rng.random() >= self.prob:
            return img
        h, w = np.asarray(img).shape[-2:] if not _is_pil(img) \
            else (img.size[1], img.size[0])
        if not _is_pil(img) and np.asarray(img).ndim == 3 \
                and np.asarray(img).shape[0] not in (1, 3):
            h, w = np.asarray(img).shape[:2]
        dx = self.scale * w / 2
        dy = self.scale * h / 2
        start = [[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]]
        end = [[float(self._rng.uniform(0, dx)),
                float(self._rng.uniform(0, dy))],
               [float(w - 1 - self._rng.uniform(0, dx)),
                float(self._rng.uniform(0, dy))],
               [float(w - 1 - self._rng.uniform(0, dx)),
                float(h - 1 - self._rng.uniform(0, dy))],
               [float(self._rng.uniform(0, dx)),
                float(h - 1 - self._rng.uniform(0, dy))]]
        return perspective(img, start, end, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    """Parity: paddle RandomErasing — erase a random rectangle with
    probability ``prob``; value None => random noise."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, seed=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace
        self._rng = np.random.default_rng(seed)

    def __call__(self, img):
        if self._rng.random() >= self.prob:
            return img
        arr = np.asarray(img) if not _is_pil(img) else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h, w = (arr.shape[1:3] if chw else arr.shape[:2])
        area = h * w
        for _ in range(10):
            target = float(self._rng.uniform(*self.scale)) * area
            ar = float(np.exp(self._rng.uniform(np.log(self.ratio[0]),
                                                np.log(self.ratio[1]))))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w and eh > 0 and ew > 0:
                i = int(self._rng.integers(0, h - eh + 1))
                j = int(self._rng.integers(0, w - ew + 1))
                if self.value is None:
                    shape = ((arr.shape[0], eh, ew) if chw
                             else (eh, ew) + arr.shape[2:])
                    v = self._rng.standard_normal(shape).astype(
                        np.float32)
                else:
                    v = self.value
                return erase(img, i, j, eh, ew, v, self.inplace)
        return img
