"""Image transforms (parity: python/paddle/vision/transforms/ —
Compose/Resize/Crop/Flip/Normalize/ToTensor and the functional forms).

All transforms operate host-side on PIL Images or numpy HWC arrays —
preprocessing belongs on CPU, overlapped with device compute via the
DataLoader prefetcher, never inside the jitted step.
"""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

try:  # PIL is the image decode path, as in the reference
    from PIL import Image

    _HAS_PIL = True
except Exception:  # pragma: no cover
    _HAS_PIL = False


def _is_pil(img):
    return _HAS_PIL and isinstance(img, Image.Image)


def _size_pair(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


# ---------------------------------------------------------------- functional


def to_tensor(img, data_format="CHW"):
    """PIL/HWC-uint8 → float32 in [0,1], CHW (paddle default) or HWC."""
    if _is_pil(img):
        img = np.asarray(img)
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def normalize(img, mean, std, data_format="CHW"):
    arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    # int size = resize the SHORTER edge to `size`, preserving aspect
    # ratio (paddle semantics); (h, w) = exact target.
    if isinstance(size, numbers.Number):
        if _is_pil(img):
            iw, ih = img.size
        else:
            ih, iw = np.asarray(img).shape[:2]
        s = int(size)
        if ih <= iw:
            h, w = s, max(1, int(round(iw * s / ih)))
        else:
            h, w = max(1, int(round(ih * s / iw))), s
    else:
        h, w = _size_pair(size)
    if _is_pil(img):
        modes = {
            "nearest": Image.NEAREST,
            "bilinear": Image.BILINEAR,
            "bicubic": Image.BICUBIC,
        }
        return img.resize((w, h), modes.get(interpolation, Image.BILINEAR))
    # numpy path: nearest / bilinear via index interpolation
    arr = np.asarray(img)
    src_h, src_w = arr.shape[:2]
    if interpolation == "nearest":
        ys = np.clip(
            np.round(np.linspace(0, src_h - 1, h)).astype(int), 0, src_h - 1
        )
        xs = np.clip(
            np.round(np.linspace(0, src_w - 1, w)).astype(int), 0, src_w - 1
        )
        return arr[ys][:, xs]
    ys = np.linspace(0, src_h - 1, h)
    xs = np.linspace(0, src_w - 1, w)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, src_h - 1)
    x1 = np.minimum(x0 + 1, src_w - 1)
    wy = (ys - y0)[:, None]
    wx = (xs - x0)[None, :]
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = arr.astype(np.float32)
    top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
    bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def crop(img, top, left, height, width):
    if _is_pil(img):
        # PIL pads out-of-bounds crops with zeros; mirror that on the
        # numpy path below so both backends return the requested size
        return img.crop((left, top, left + width, top + height))
    arr = np.asarray(img)
    out = arr[max(top, 0): max(top + height, 0),
              max(left, 0): max(left + width, 0)]
    if out.shape[0] != height or out.shape[1] != width:
        padded = np.zeros((height, width) + arr.shape[2:], dtype=arr.dtype)
        oy = max(-top, 0)
        ox = max(-left, 0)
        padded[oy:oy + out.shape[0], ox:ox + out.shape[1]] = out
        return padded
    return out


def center_crop(img, size):
    h, w = _size_pair(size)
    if _is_pil(img):
        iw, ih = img.size
    else:
        ih, iw = np.asarray(img).shape[:2]
    return crop(img, max(0, (ih - h) // 2), max(0, (iw - w) // 2), h, w)


def hflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return np.asarray(img)[:, ::-1]


def vflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return np.asarray(img)[::-1]


# ------------------------------------------------------------------ classes


class BaseTransform:
    def __call__(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW"):
        # scalars broadcast over whatever channel count the image has
        # (a grayscale input must stay single-channel)
        if isinstance(mean, numbers.Number):
            mean = [mean]
        if isinstance(std, numbers.Number):
            std = [std]
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = _size_pair(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad, mode="constant")
        h, w = self.size
        ih, iw = arr.shape[:2]
        top = random.randint(0, max(0, ih - h))
        left = random.randint(0, max(0, iw - w))
        return crop(arr, top, left, h, w)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if random.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = _size_pair(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img) if not _is_pil(img) else img
        if _is_pil(arr):
            iw, ih = arr.size
        else:
            ih, iw = arr.shape[:2]
        area = ih * iw
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            w = int(round((target * ar) ** 0.5))
            h = int(round((target / ar) ** 0.5))
            if 0 < w <= iw and 0 < h <= ih:
                top = random.randint(0, ih - h)
                left = random.randint(0, iw - w)
                patch = crop(img, top, left, h, w)
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(img, min(ih, iw)), self.size,
                      self.interpolation)


class Transpose(BaseTransform):
    """HWC → CHW (paddle parity for pipelines that skip ToTensor)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)
