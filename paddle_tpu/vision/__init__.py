"""Vision domain API (parity: python/paddle/vision/ — transforms,
datasets, model zoo).

Host-side preprocessing stays numpy/PIL (it runs on CPU feeding the
device prefetch pipeline in ``paddle_tpu.io``); models are ordinary
``Layer`` trees compiled by XLA, NHWC-internal where it matters for the
MXU.
"""

from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from .models import (  # noqa: F401
    AlexNet,
    DenseNet,
    ResNet,
    ShuffleNetV2,
    SqueezeNet,
    VGG,
    alexnet,
    densenet121,
    shufflenet_v2_x1_0,
    squeezenet1_1,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
    mobilenet_v2,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)

from . import ops  # noqa: F401,E402


_image_backend = "pil"


def set_image_backend(backend):
    """Parity: paddle.vision.set_image_backend ('pil' | 'cv2' |
    'tensor'). Decoding here is PIL/numpy-based; 'cv2' is accepted and
    served by the same path."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Parity: paddle.vision.image_load — ndarray/PIL image from disk."""
    import numpy as np

    b = backend or _image_backend
    try:
        from PIL import Image
    except ImportError:
        Image = None
    if Image is not None:
        img = Image.open(path)
        if b in ("cv2", "tensor"):
            return np.asarray(img)
        return img
    raise RuntimeError("image_load needs PIL (not available)")
