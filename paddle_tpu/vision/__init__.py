"""Vision domain API (parity: python/paddle/vision/ — transforms,
datasets, model zoo).

Host-side preprocessing stays numpy/PIL (it runs on CPU feeding the
device prefetch pipeline in ``paddle_tpu.io``); models are ordinary
``Layer`` trees compiled by XLA, NHWC-internal where it matters for the
MXU.
"""

from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from .models import (  # noqa: F401
    AlexNet,
    DenseNet,
    ResNet,
    ShuffleNetV2,
    SqueezeNet,
    VGG,
    alexnet,
    densenet121,
    shufflenet_v2_x1_0,
    squeezenet1_1,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
    mobilenet_v2,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)

from . import ops  # noqa: F401,E402
