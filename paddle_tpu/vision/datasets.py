"""Vision datasets (parity: python/paddle/vision/datasets/ — MNIST,
Cifar10/100, DatasetFolder/ImageFolder).

This sandbox has zero egress, so datasets load from *local* files only
(``download=True`` raises with a clear message); ``FakeData`` provides a
deterministic synthetic stand-in for tests and smoke training runs —
the same role the reference's unittests fill with fake readers.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io import Dataset

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".webp")


class FakeData(Dataset):
    """Deterministic synthetic image-classification dataset."""

    def __init__(self, num_samples=64, image_shape=(32, 32, 3),
                 num_classes=10, transform: Optional[Callable] = None):
        # default is HWC uint8 — the layout every transform expects
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.default_rng(idx)
        img = rng.integers(
            0, 256, size=self.image_shape, dtype=np.uint8
        ).astype(np.uint8)
        label = int(rng.integers(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


def _no_download(name):
    raise RuntimeError(
        f"{name}: download is unavailable in this environment (no network); "
        "pass the path to locally present data files"
    )


class MNIST(Dataset):
    """MNIST from local idx/idx-gz files (parity: paddle.vision.datasets.MNIST).

    ``image_path``/``label_path`` point at the standard
    ``*-images-idx3-ubyte(.gz)`` / ``*-labels-idx1-ubyte(.gz)`` files.
    """

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        if image_path is None or label_path is None:
            _no_download("MNIST")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        assert len(self.images) == len(self.labels)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


class Cifar10(Dataset):
    """CIFAR-10 from the local ``cifar-10-python.tar.gz`` (parity:
    paddle.vision.datasets.Cifar10)."""

    _batches_train = [f"data_batch_{i}" for i in range(1, 6)]
    _batches_test = ["test_batch"]

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False):
        if data_file is None:
            _no_download("Cifar10")
        names = self._batches_train if mode == "train" else self._batches_test
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in names:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    images.append(
                        np.asarray(d[b"data"], dtype=np.uint8).reshape(
                            -1, 3, 32, 32
                        )
                    )
                    labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.images = np.concatenate(images, axis=0)
        self.labels = np.asarray(labels, dtype=np.int64)
        self.transform = transform

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = np.transpose(self.images[idx], (1, 2, 0))  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class DatasetFolder(Dataset):
    """class-per-subdirectory layout (parity: paddle DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=IMAGE_EXTS,
                 transform=None):
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise ValueError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    if fname.lower().endswith(tuple(extensions)):
                        self.samples.append(
                            (os.path.join(dirpath, fname), self.class_to_idx[c])
                        )
        self.loader = loader or self._pil_loader
        self.transform = transform

    @staticmethod
    def _pil_loader(path):
        from PIL import Image

        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


class ImageFolder(DatasetFolder):
    """Unlabeled flat folder of images (parity: paddle ImageFolder)."""

    def __init__(self, root, loader=None, extensions=IMAGE_EXTS,
                 transform=None):
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(dirpath, fname), -1))
        self.loader = loader or DatasetFolder._pil_loader
        self.transform = transform

    def __getitem__(self, idx):
        path, _ = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return (img,)


class FashionMNIST(MNIST):
    """Parity: paddle.vision.datasets.FashionMNIST — identical idx file
    format, different corpus."""


class Cifar100(Cifar10):
    """Parity: paddle.vision.datasets.Cifar100 — the
    ``cifar-100-python.tar.gz`` layout ('train'/'test' members,
    fine_labels)."""

    _batches_train = ["train"]
    _batches_test = ["test"]


class Flowers(Dataset):
    """Oxford-102 Flowers from local files (parity:
    paddle.vision.datasets.Flowers): ``data_file`` is the image tarball
    (jpg files), ``label_file`` the imagelabels .mat, ``setid_file``
    the split ids .mat."""

    _split_key = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend="cv2"):
        if data_file is None or label_file is None or setid_file is None:
            _no_download("Flowers")
        from scipy.io import loadmat

        labels = loadmat(label_file)["labels"][0]
        ids = loadmat(setid_file)[self._split_key[mode]][0]
        self.transform = transform
        self._records = []
        with tarfile.open(data_file, "r:*") as tf:
            by_name = {os.path.basename(m.name): m
                       for m in tf.getmembers() if m.isfile()}
            for i in ids:
                name = f"image_{int(i):05d}.jpg"
                if name in by_name:
                    data = tf.extractfile(by_name[name]).read()
                    self._records.append((data, int(labels[i - 1]) - 1))

    def __len__(self):
        return len(self._records)

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        data, label = self._records[idx]
        img = np.asarray(Image.open(_io.BytesIO(data)).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs from the local devkit tarball
    (parity: paddle.vision.datasets.VOC2012): yields (image, label
    mask) uint8 arrays per the split list."""

    _lists = {"train": "train.txt", "valid": "val.txt",
              "trainval": "trainval.txt", "test": "val.txt"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if data_file is None:
            _no_download("VOC2012")
        self.transform = transform
        with tarfile.open(data_file, "r:*") as tf:
            members = {m.name: m for m in tf.getmembers() if m.isfile()}
            list_suffix = ("ImageSets/Segmentation/"
                           + self._lists[mode])
            list_name = next(
                (n for n in members if n.endswith(list_suffix)), None)
            if list_name is None:
                raise FileNotFoundError(list_suffix)
            # devkit root derived once -> O(1) member lookups per name
            root = list_name[: -len(list_suffix)]
            names = tf.extractfile(members[list_name]).read() \
                .decode().split()
            # store COMPRESSED bytes; decode per __getitem__ (the
            # trainval split is ~2.9k full-res pairs — eager decode
            # would cost multi-GB of resident uint8)
            self._records = []
            for n in names:
                img_m = members.get(f"{root}JPEGImages/{n}.jpg")
                seg_m = members.get(f"{root}SegmentationClass/{n}.png")
                if img_m is None or seg_m is None:
                    continue
                self._records.append(
                    (tf.extractfile(img_m).read(),
                     tf.extractfile(seg_m).read()))

    def __len__(self):
        return len(self._records)

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        img_b, seg_b = self._records[idx]
        img = np.asarray(Image.open(_io.BytesIO(img_b)).convert("RGB"))
        seg = np.asarray(Image.open(_io.BytesIO(seg_b)))
        if self.transform is not None:
            img = self.transform(img)
        return img, seg
