"""GoogLeNet (Inception v1) and Inception-v3 (parity:
python/paddle/vision/models/{googlenet,inceptionv3}.py).

Structure follows the papers exactly (the reference zoos do too), so
shapes and parameter counts line up. Aux classifier heads exist and run
in training mode (paddle's GoogLeNet returns (out, aux1, aux2) when
training); inference returns the main logits only.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.module import Layer
from ...nn import functional as F
from ...nn.layer.common import Dropout, Linear, Sequential
from ...nn.layer.conv import AdaptiveAvgPool2D, Conv2D
from ...nn.layer.norm import BatchNorm2D


class _ConvBN(Layer):
    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


# --------------------------------------------------------------- GoogLeNet
class _InceptionV1(Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        self.b1 = _ConvBN(cin, c1, 1)
        self.b2 = Sequential(_ConvBN(cin, c3r, 1),
                             _ConvBN(c3r, c3, 3, padding=1))
        self.b3 = Sequential(_ConvBN(cin, c5r, 1),
                             _ConvBN(c5r, c5, 3, padding=1))
        self.b4 = _ConvBN(cin, pool_proj, 1)

    def forward(self, x):
        p = F.max_pool2d(x, 3, 1, padding=1)
        return jnp.concatenate(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(p)], axis=1)


class _AuxV1(Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.conv = _ConvBN(cin, 128, 1)
        self.fc1 = Linear(2048, 1024)
        self.fc2 = Linear(1024, num_classes)
        self.dropout = Dropout(0.7)

    def forward(self, x):
        x = F.adaptive_avg_pool2d(x, 4)
        x = self.conv(x).reshape(x.shape[0], -1)
        x = F.relu(self.fc1(x))
        return self.fc2(self.dropout(x))


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3),
        )
        self.conv2 = _ConvBN(64, 64, 1)
        self.conv3 = _ConvBN(64, 192, 3, padding=1)
        self.i3a = _InceptionV1(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _InceptionV1(256, 128, 128, 192, 32, 96, 64)
        self.i4a = _InceptionV1(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _InceptionV1(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _InceptionV1(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _InceptionV1(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _InceptionV1(528, 256, 160, 320, 32, 128, 128)
        self.i5a = _InceptionV1(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _InceptionV1(832, 384, 192, 384, 48, 128, 128)
        self.aux1 = _AuxV1(512, num_classes)
        self.aux2 = _AuxV1(528, num_classes)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = F.max_pool2d(self.stem(x), 3, 2, padding=1)
        x = F.max_pool2d(self.conv3(self.conv2(x)), 3, 2, padding=1)
        x = self.i3b(self.i3a(x))
        x = F.max_pool2d(x, 3, 2, padding=1)
        x = self.i4a(x)
        aux1 = self.aux1(x) if self.training else None
        x = self.i4c(self.i4b(x))
        x = self.i4d(x)
        aux2 = self.aux2(x) if self.training else None
        x = self.i4e(x)
        x = F.max_pool2d(x, 3, 2, padding=1)
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.reshape(x.shape[0], -1)))
        if self.training:
            return x, aux1, aux2
        return x


def googlenet(**kwargs):
    return GoogLeNet(**kwargs)


# ------------------------------------------------------------- Inception v3
class _IncA(Layer):
    def __init__(self, cin, pool_ch):
        super().__init__()
        self.b1 = _ConvBN(cin, 64, 1)
        self.b5 = Sequential(_ConvBN(cin, 48, 1),
                             _ConvBN(48, 64, 5, padding=2))
        self.b3 = Sequential(_ConvBN(cin, 64, 1),
                             _ConvBN(64, 96, 3, padding=1),
                             _ConvBN(96, 96, 3, padding=1))
        self.bp = _ConvBN(cin, pool_ch, 1)

    def forward(self, x):
        p = F.avg_pool2d(x, 3, 1, padding=1)
        return jnp.concatenate(
            [self.b1(x), self.b5(x), self.b3(x), self.bp(p)], axis=1)


class _IncB(Layer):  # grid reduction 35 -> 17
    def __init__(self, cin):
        super().__init__()
        self.b3 = _ConvBN(cin, 384, 3, stride=2)
        self.b3d = Sequential(_ConvBN(cin, 64, 1),
                              _ConvBN(64, 96, 3, padding=1),
                              _ConvBN(96, 96, 3, stride=2))

    def forward(self, x):
        p = F.max_pool2d(x, 3, 2)
        return jnp.concatenate([self.b3(x), self.b3d(x), p], axis=1)


class _IncC(Layer):  # 17x17 factorized 7x7
    def __init__(self, cin, ch7):
        super().__init__()
        self.b1 = _ConvBN(cin, 192, 1)
        self.b7 = Sequential(
            _ConvBN(cin, ch7, 1),
            _ConvBN(ch7, ch7, (1, 7), padding=(0, 3)),
            _ConvBN(ch7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            _ConvBN(cin, ch7, 1),
            _ConvBN(ch7, ch7, (7, 1), padding=(3, 0)),
            _ConvBN(ch7, ch7, (1, 7), padding=(0, 3)),
            _ConvBN(ch7, ch7, (7, 1), padding=(3, 0)),
            _ConvBN(ch7, 192, (1, 7), padding=(0, 3)))
        self.bp = _ConvBN(cin, 192, 1)

    def forward(self, x):
        p = F.avg_pool2d(x, 3, 1, padding=1)
        return jnp.concatenate(
            [self.b1(x), self.b7(x), self.b7d(x), self.bp(p)], axis=1)


class _IncD(Layer):  # grid reduction 17 -> 8
    def __init__(self, cin):
        super().__init__()
        self.b3 = Sequential(_ConvBN(cin, 192, 1),
                             _ConvBN(192, 320, 3, stride=2))
        self.b7 = Sequential(
            _ConvBN(cin, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))

    def forward(self, x):
        p = F.max_pool2d(x, 3, 2)
        return jnp.concatenate([self.b3(x), self.b7(x), p], axis=1)


class _IncE(Layer):  # 8x8 expanded filter bank
    def __init__(self, cin):
        super().__init__()
        self.b1 = _ConvBN(cin, 320, 1)
        self.b3_stem = _ConvBN(cin, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = Sequential(_ConvBN(cin, 448, 1),
                                   _ConvBN(448, 384, 3, padding=1))
        self.b3d_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = _ConvBN(cin, 192, 1)

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        p = F.avg_pool2d(x, 3, 1, padding=1)
        return jnp.concatenate(
            [self.b1(x),
             self.b3_a(s), self.b3_b(s),
             self.b3d_a(d), self.b3d_b(d),
             self.bp(p)], axis=1)


class _AuxV3(Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.conv0 = _ConvBN(cin, 128, 1)
        self.conv1 = _ConvBN(128, 768, 5)
        self.fc = Linear(768, num_classes)

    def forward(self, x):
        x = F.avg_pool2d(x, 5, 3)
        x = self.conv1(self.conv0(x))
        x = F.adaptive_avg_pool2d(x, 1)
        return self.fc(x.reshape(x.shape[0], -1))


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            _ConvBN(3, 32, 3, stride=2),
            _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1),
        )
        self.conv3 = _ConvBN(64, 80, 1)
        self.conv4 = _ConvBN(80, 192, 3)
        self.a1 = _IncA(192, 32)
        self.a2 = _IncA(256, 64)
        self.a3 = _IncA(288, 64)
        self.b = _IncB(288)
        self.c1 = _IncC(768, 128)
        self.c2 = _IncC(768, 160)
        self.c3 = _IncC(768, 160)
        self.c4 = _IncC(768, 192)
        self.aux = _AuxV3(768, num_classes)
        self.d = _IncD(768)
        self.e1 = _IncE(1280)
        self.e2 = _IncE(2048)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = F.max_pool2d(self.stem(x), 3, 2)
        x = F.max_pool2d(self.conv4(self.conv3(x)), 3, 2)
        x = self.a3(self.a2(self.a1(x)))
        x = self.b(x)
        x = self.c4(self.c3(self.c2(self.c1(x))))
        aux = self.aux(x) if self.training else None
        x = self.d(x)
        x = self.e2(self.e1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.reshape(x.shape[0], -1)))
        if self.training:
            return x, aux
        return x


def inception_v3(**kwargs):
    return InceptionV3(**kwargs)
