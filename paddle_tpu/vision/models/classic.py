"""Classic CNN zoo (parity: python/paddle/vision/models/ — vgg.py,
alexnet.py, squeezenet.py, densenet.py, shufflenetv2.py).

All are plain conv stacks; XLA fuses conv+BN+act per block. Constructors
mirror paddle's (``num_classes``, ``with_pool``, VGG ``batch_norm``
defaulting off like the reference); no pretrained weights (zero
egress) — same-architecture state dicts load via ``set_state_dict``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.module import Layer
from ...nn import functional as F
from ...nn.layer.common import Dropout, Linear
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D


class _ConvBNReLU(Layer):
    def __init__(self, cin, cout, k=3, stride=1, padding=1, groups=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(cout)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


class _MaxPool2x2(Layer):
    def forward(self, x):
        return F.max_pool2d(x, 2, 2)


class _ConvReLU(Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.conv = Conv2D(cin, cout, 3, padding=1)

    def forward(self, x):
        return F.relu(self.conv(x))


# ---------------------------------------------------------------------------
# VGG
# ---------------------------------------------------------------------------
_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
         "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
         512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    def __init__(self, depth=16, num_classes=1000, batch_norm=False,
                 with_pool=True):
        super().__init__()
        from ...nn.layer.common import LayerList

        layers = []
        cin = 3
        for v in _VGG_CFGS[depth]:
            if v == "M":
                layers.append(_MaxPool2x2())
            elif batch_norm:
                layers.append(_ConvBNReLU(cin, v))
                cin = v
            else:
                layers.append(_ConvReLU(cin, v))
                cin = v
        self.features = LayerList(layers)
        self.batch_norm = batch_norm
        self.with_pool = with_pool
        self.classifier = LayerList([
            Linear(512 * 7 * 7, 4096), Linear(4096, 4096),
            Linear(4096, num_classes),
        ])
        self.dropout = Dropout(0.5)

    def forward(self, x):
        for m in self.features:
            x = m(x)
        if self.with_pool:
            x = F.adaptive_avg_pool2d(x, (7, 7))
        x = x.reshape(x.shape[0], -1)
        x = self.dropout(F.relu(self.classifier[0](x)))
        x = self.dropout(F.relu(self.classifier[1](x)))
        return self.classifier[2](x)


def vgg11(**kw):
    return VGG(11, **kw)


def vgg13(**kw):
    return VGG(13, **kw)


def vgg16(**kw):
    return VGG(16, **kw)


def vgg19(**kw):
    return VGG(19, **kw)


# ---------------------------------------------------------------------------
# AlexNet
# ---------------------------------------------------------------------------
class AlexNet(Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.c1 = Conv2D(3, 64, 11, stride=4, padding=2)
        self.c2 = Conv2D(64, 192, 5, padding=2)
        self.c3 = Conv2D(192, 384, 3, padding=1)
        self.c4 = Conv2D(384, 256, 3, padding=1)
        self.c5 = Conv2D(256, 256, 3, padding=1)
        self.fc1 = Linear(256 * 6 * 6, 4096)
        self.fc2 = Linear(4096, 4096)
        self.fc3 = Linear(4096, num_classes)
        self.dropout = Dropout(0.5)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.c1(x)), 3, 2)
        x = F.max_pool2d(F.relu(self.c2(x)), 3, 2)
        x = F.relu(self.c3(x))
        x = F.relu(self.c4(x))
        x = F.max_pool2d(F.relu(self.c5(x)), 3, 2)
        x = F.adaptive_avg_pool2d(x, (6, 6)).reshape(x.shape[0], -1)
        x = self.dropout(F.relu(self.fc1(x)))
        x = self.dropout(F.relu(self.fc2(x)))
        return self.fc3(x)


def alexnet(**kw):
    return AlexNet(**kw)


# ---------------------------------------------------------------------------
# SqueezeNet
# ---------------------------------------------------------------------------
class _Fire(Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Conv2D(cin, squeeze, 1)
        self.expand1 = Conv2D(squeeze, e1, 1)
        self.expand3 = Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = F.relu(self.squeeze(x))
        return jnp.concatenate(
            [F.relu(self.expand1(s)), F.relu(self.expand3(s))], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.1", num_classes=1000):
        super().__init__()
        from ...nn.layer.common import LayerList

        if version not in ("1.0", "1.1"):
            raise ValueError(f"SqueezeNet: unknown version {version!r}")
        self.version = version
        if version == "1.1":
            self.conv1 = Conv2D(3, 64, 3, stride=2)
            self.fires = LayerList([
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            ])
            self._pool_after = (1, 3)  # v1.1 placement
        else:
            self.conv1 = Conv2D(3, 96, 7, stride=2)
            self.fires = LayerList([
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            ])
            self._pool_after = (2, 6)  # v1.0 placement
        self.conv_final = Conv2D(512, num_classes, 1)
        self.dropout = Dropout(0.5)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.conv1(x)), 3, 2)
        for i, fire in enumerate(self.fires):
            x = fire(x)
            if i in self._pool_after:
                x = F.max_pool2d(x, 3, 2)
        x = F.relu(self.conv_final(self.dropout(x)))
        return F.adaptive_avg_pool2d(x, (1, 1)).reshape(x.shape[0], -1)


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)


# ---------------------------------------------------------------------------
# DenseNet
# ---------------------------------------------------------------------------
class _DenseLayer(Layer):
    def __init__(self, cin, growth, bn_size=4):
        super().__init__()
        self.bn1 = BatchNorm2D(cin)
        self.conv1 = Conv2D(cin, bn_size * growth, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth)
        self.conv2 = Conv2D(bn_size * growth, growth, 3, padding=1,
                            bias_attr=False)

    def forward(self, x):
        h = self.conv1(F.relu(self.bn1(x)))
        h = self.conv2(F.relu(self.bn2(h)))
        return jnp.concatenate([x, h], axis=1)


class _Transition(Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.bn = BatchNorm2D(cin)
        self.conv = Conv2D(cin, cout, 1, bias_attr=False)

    def forward(self, x):
        x = self.conv(F.relu(self.bn(x)))
        return F.avg_pool2d(x, 2, 2)


class DenseNet(Layer):
    def __init__(self, layers=121, growth_rate=32, num_classes=1000):
        super().__init__()
        from ...nn.layer.common import LayerList

        block_cfg = {121: (6, 12, 24, 16), 169: (6, 12, 32, 32),
                     201: (6, 12, 48, 32)}[layers]
        c = 64
        self.stem = Conv2D(3, c, 7, stride=2, padding=3, bias_attr=False)
        self.stem_bn = BatchNorm2D(c)
        blocks = []
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(c, growth_rate))
                c += growth_rate
            if bi != len(block_cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c //= 2
        self.blocks = LayerList(blocks)
        self.final_bn = BatchNorm2D(c)
        self.classifier = Linear(c, num_classes)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.stem_bn(self.stem(x))), 3, 2,
                         padding=1)
        for blk in self.blocks:
            x = blk(x)
        x = F.relu(self.final_bn(x))
        x = F.adaptive_avg_pool2d(x, (1, 1)).reshape(x.shape[0], -1)
        return self.classifier(x)


def densenet121(**kw):
    return DenseNet(121, **kw)


# ---------------------------------------------------------------------------
# ShuffleNetV2
# ---------------------------------------------------------------------------
def _channel_shuffle(x, groups=2):
    n, c, h, w = x.shape
    return x.reshape(n, groups, c // groups, h, w) \
        .swapaxes(1, 2).reshape(n, c, h, w)


class _ShuffleUnit(Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.stride = stride
        branch = cout // 2
        if stride > 1:
            self.b1_dw = Conv2D(cin, cin, 3, stride=stride, padding=1,
                                groups=cin, bias_attr=False)
            self.b1_bn1 = BatchNorm2D(cin)
            self.b1_pw = Conv2D(cin, branch, 1, bias_attr=False)
            self.b1_bn2 = BatchNorm2D(branch)
            b2_in = cin
        else:
            b2_in = cin // 2
        self.b2_pw1 = Conv2D(b2_in, branch, 1, bias_attr=False)
        self.b2_bn1 = BatchNorm2D(branch)
        self.b2_dw = Conv2D(branch, branch, 3, stride=stride, padding=1,
                            groups=branch, bias_attr=False)
        self.b2_bn2 = BatchNorm2D(branch)
        self.b2_pw2 = Conv2D(branch, branch, 1, bias_attr=False)
        self.b2_bn3 = BatchNorm2D(branch)

    def forward(self, x):
        if self.stride > 1:
            left = self.b1_bn2(self.b1_pw(self.b1_bn1(self.b1_dw(x))))
            left = F.relu(left)
            right_in = x
        else:
            left, right_in = jnp.split(x, 2, axis=1)
        h = F.relu(self.b2_bn1(self.b2_pw1(right_in)))
        h = self.b2_bn2(self.b2_dw(h))
        h = F.relu(self.b2_bn3(self.b2_pw2(h)))
        return _channel_shuffle(jnp.concatenate([left, h], axis=1))


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        from ...nn.layer.common import LayerList

        stage_out = {0.5: (48, 96, 192, 1024),
                     1.0: (116, 232, 464, 1024),
                     1.5: (176, 352, 704, 1024)}[scale]
        self.stem = _ConvBNReLU(3, 24, 3, stride=2)
        units = []
        cin = 24
        for cout, repeat in zip(stage_out[:3], (4, 8, 4)):
            units.append(_ShuffleUnit(cin, cout, 2))
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(cout, cout, 1))
            cin = cout
        self.units = LayerList(units)
        self.head = _ConvBNReLU(cin, stage_out[3], 1, padding=0)
        self.classifier = Linear(stage_out[3], num_classes)

    def forward(self, x):
        x = F.max_pool2d(self.stem(x), 3, 2, padding=1)
        for u in self.units:
            x = u(x)
        x = self.head(x)
        x = F.adaptive_avg_pool2d(x, (1, 1)).reshape(x.shape[0], -1)
        return self.classifier(x)


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(1.0, **kw)


# ---------------------------------------------------------------------------
# LeNet (parity: paddle.vision.models.LeNet — the MNIST 1x28x28 config)
# ---------------------------------------------------------------------------
class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        from ...nn.layer.common import Linear, Sequential

        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1),
        )
        self.conv2 = Conv2D(6, 16, 5, stride=1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(400, 120)
            self.fc1 = Linear(120, 84)
            self.fc2 = Linear(84, num_classes)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.features(x)), 2, 2)
        x = F.max_pool2d(F.relu(self.conv2(x)), 2, 2)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = F.relu(self.fc(x))
            x = F.relu(self.fc1(x))
            x = self.fc2(x)
        return x
