"""MobileNetV2 (parity: python/paddle/vision/models/mobilenetv2.py —
inverted residuals with depthwise separable convs).

Depthwise convs lower to XLA grouped convolution (feature_group_count);
on TPU they run on the VPU rather than the MXU, so MobileNet is a
bandwidth-shape parity model, not a perf flagship.
"""

from __future__ import annotations

from ...core.module import Layer
from ...nn import functional as F
from ...nn.layer.common import Linear, Sequential
from ...nn.layer.conv import AdaptiveAvgPool2D, Conv2D
from ...nn.layer.norm import BatchNorm2D


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(Layer):
    def __init__(self, in_ch, out_ch, kernel=3, stride=1, groups=1):
        super().__init__()
        pad = (kernel - 1) // 2
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride, padding=pad,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(out_ch)

    def forward(self, x):
        return F.relu6(self.bn(self.conv(x)))


class InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden, kernel=1))
        layers.append(ConvBNReLU(hidden, hidden, stride=stride, groups=hidden))
        self.body = Sequential(*layers)
        self.project = Conv2D(hidden, oup, 1, bias_attr=False)
        self.project_bn = BatchNorm2D(oup)

    def forward(self, x):
        out = self.project_bn(self.project(self.body(x)))
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [
            # t, c, n, s
            (1, 16, 1, 1),
            (6, 24, 2, 2),
            (6, 32, 3, 2),
            (6, 64, 4, 2),
            (6, 96, 3, 1),
            (6, 160, 3, 2),
            (6, 320, 1, 1),
        ]
        input_channel = _make_divisible(32 * scale)
        self.last_channel = _make_divisible(1280 * max(1.0, scale))
        features = [ConvBNReLU(3, input_channel, stride=2)]
        for t, c, n, s in cfg:
            out_ch = _make_divisible(c * scale)
            for i in range(n):
                features.append(
                    InvertedResidual(input_channel, out_ch,
                                     s if i == 0 else 1, t)
                )
                input_channel = out_ch
        features.append(ConvBNReLU(input_channel, self.last_channel, kernel=1))
        self.features = Sequential(*features)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Linear(self.last_channel, num_classes)

    def forward(self, x, labels=None):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.classifier(x)
        if labels is not None:
            return F.cross_entropy(x, labels)
        return x


def mobilenet_v2(scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
