"""ResNet family (parity: python/paddle/vision/models/resnet.py —
BasicBlock/BottleneckBlock, resnet18..152).

TPU notes: 7x7-stride-2 stem, 3x3/1x1 convs all lower to XLA convolution
which tiles onto the MXU; BN runs frozen-stats inside jitted steps (see
nn.layer.norm.BatchNorm2D) matching how the reference's distributed
vision recipes freeze BN; for from-scratch jit training, pass
``norm_layer=GroupNorm``-style factory.

Layout fast path: ``channels_last`` (default: follow
``PT_FLAGS_conv_layout``, auto = NHWC on TPU) transposes once at entry
and runs the whole conv/BN/pool body channels-last — TPU's native conv
layout — with the NCHW paddle convention preserved at the API boundary.
The residual blocks themselves are layout-neutral (convs/norms resolve
via ``nn.layout``; ReLU and adds are elementwise).
"""

from __future__ import annotations

from ...core.module import Layer
from ...nn import functional as F
from ...nn import layout
from ...nn.layer.common import Linear, Sequential
from ...nn.layer.conv import AdaptiveAvgPool2D, Conv2D, MaxPool2D
from ...nn.layer.norm import BatchNorm2D


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 norm_layer=BatchNorm2D):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return F.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 norm_layer=BatchNorm2D, groups=1, base_width=64):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = Conv2D(width, planes * self.expansion, 1,
                            bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return F.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True,
                 norm_layer=BatchNorm2D, in_channels=3, groups=1,
                 width=64, channels_last=None):
        super().__init__()
        self.channels_last = channels_last
        self.inplanes = 64
        self.norm_layer = norm_layer
        self.groups = groups
        self.base_width = width
        self.conv1 = Conv2D(in_channels, 64, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = norm_layer(64)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                self.norm_layer(planes * block.expansion),
            )
        extra = ({"groups": self.groups, "base_width": self.base_width}
                 if block is BottleneckBlock else {})
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.norm_layer, **extra)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(
                block(self.inplanes, planes, norm_layer=self.norm_layer,
                      **extra)
            )
        return Sequential(*layers)

    def forward(self, x, labels=None):
        cl = layout.decide(self.channels_last)
        if cl:
            x = layout.nchw_to_nhwc(x)
        with layout.channels_last_scope(cl):
            x = F.relu(self.bn1(self.conv1(x)))
            x = self.maxpool(x)
            x = self.layer1(x)
            x = self.layer2(x)
            x = self.layer3(x)
            x = self.layer4(x)
            if self.with_pool:
                x = self.avgpool(x)
        if cl:
            x = layout.nhwc_to_nchw(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        if labels is not None:
            return F.cross_entropy(x, labels)
        return x


def _resnet(block, depth_cfg, **kwargs):
    return ResNet(block, depth_cfg, **kwargs)


def resnet18(**kwargs):
    return _resnet(BasicBlock, (2, 2, 2, 2), **kwargs)


def resnet34(**kwargs):
    return _resnet(BasicBlock, (3, 4, 6, 3), **kwargs)


def resnet50(**kwargs):
    return _resnet(BottleneckBlock, (3, 4, 6, 3), **kwargs)


def resnet101(**kwargs):
    return _resnet(BottleneckBlock, (3, 4, 23, 3), **kwargs)


def resnet152(**kwargs):
    return _resnet(BottleneckBlock, (3, 8, 36, 3), **kwargs)


def wide_resnet50_2(**kwargs):
    """Parity: paddle wide_resnet50_2 — bottleneck width doubled."""
    return _resnet(BottleneckBlock, (3, 4, 6, 3), width=128, **kwargs)


def wide_resnet101_2(**kwargs):
    return _resnet(BottleneckBlock, (3, 4, 23, 3), width=128, **kwargs)


def resnext50_32x4d(**kwargs):
    """Parity: paddle resnext50_32x4d — 32 groups x 4-wide."""
    return _resnet(BottleneckBlock, (3, 4, 6, 3), groups=32, width=4,
                   **kwargs)


def resnext101_32x4d(**kwargs):
    return _resnet(BottleneckBlock, (3, 4, 23, 3), groups=32, width=4,
                   **kwargs)
