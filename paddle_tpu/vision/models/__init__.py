"""Vision model zoo (parity: python/paddle/vision/models/)."""

from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .inception import (  # noqa: F401
    GoogLeNet,
    InceptionV3,
    googlenet,
    inception_v3,
)
from .mobilenetv3 import (  # noqa: F401
    MobileNetV3,
    mobilenet_v3_large,
    mobilenet_v3_small,
)
from .resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    resnext50_32x4d,
    resnext101_32x4d,
    wide_resnet50_2,
    wide_resnet101_2,
)

from .classic import (  # noqa: F401,E402
    VGG,
    AlexNet,
    DenseNet,
    ShuffleNetV2,
    LeNet,
    SqueezeNet,
    alexnet,
    densenet121,
    shufflenet_v2_x1_0,
    squeezenet1_0,
    squeezenet1_1,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
)
