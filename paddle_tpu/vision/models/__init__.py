"""Vision model zoo (parity: python/paddle/vision/models/)."""

from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)
