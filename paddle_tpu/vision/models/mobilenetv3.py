"""MobileNetV3 (parity: python/paddle/vision/models/mobilenetv3.py —
bneck blocks with squeeze-excitation and hardswish).

Same TPU note as V2: depthwise convs are VPU work; parity model.
Config tables follow the paper/torchvision/paddle exactly, so parameter
counts line up with the reference zoo.
"""

from __future__ import annotations

from ...core.module import Layer
from ...nn import functional as F
from ...nn.layer.common import Dropout, Linear, Sequential
from ...nn.layer.conv import AdaptiveAvgPool2D, Conv2D
from ...nn.layer.norm import BatchNorm2D
from .mobilenetv2 import _make_divisible


class _SqueezeExcite(Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.fc1 = Conv2D(ch, squeeze_ch, 1)
        self.fc2 = Conv2D(squeeze_ch, ch, 1)

    def forward(self, x):
        s = F.adaptive_avg_pool2d(x, 1)
        s = F.relu(self.fc1(s))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _ConvBNAct(Layer):
    def __init__(self, cin, cout, k=3, stride=1, groups=1, act="hardswish"):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride,
                           padding=(k - 1) // 2, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        if self.act == "hardswish":
            return F.hardswish(x)
        if self.act == "relu":
            return F.relu(x)
        return x


class _Bneck(Layer):
    def __init__(self, cin, exp, cout, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if exp != cin:
            layers.append(_ConvBNAct(cin, exp, 1, act=act))
        layers.append(_ConvBNAct(exp, exp, k, stride=stride, groups=exp,
                                 act=act))
        if use_se:
            layers.append(_SqueezeExcite(exp, _make_divisible(exp // 4)))
        layers.append(_ConvBNAct(exp, cout, 1, act="none"))
        self.body = Sequential(*layers)

    def forward(self, x):
        out = self.body(x)
        return x + out if self.use_res else out


# (kernel, exp, out, SE, act, stride) — the paper's Tables 1 & 2
_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        inp = _make_divisible(16 * scale)
        self.stem = _ConvBNAct(3, inp, 3, stride=2, act="hardswish")
        blocks = []
        for k, exp, cout, se, act, s in config:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(cout * scale)
            blocks.append(_Bneck(inp, exp_c, out_c, k, s, se, act))
            inp = out_c
        self.blocks = Sequential(*blocks)
        last_exp = _make_divisible(config[-1][1] * scale)
        self.conv_last = _ConvBNAct(inp, last_exp, 1, act="hardswish")
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(last_exp, last_channel),
                Dropout(0.2),
                Linear(last_channel, num_classes),
            )
            self._head_act_after = 0  # hardswish after the first Linear

    def forward(self, x):
        x = self.conv_last(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.reshape(x.shape[0], -1)
            x = self.classifier[0](x)
            x = F.hardswish(x)
            x = self.classifier[1](x)
            x = self.classifier[2](x)
        return x


def mobilenet_v3_large(scale=1.0, **kwargs):
    return MobileNetV3(_LARGE, _make_divisible(1280 * scale), scale,
                       **kwargs)


def mobilenet_v3_small(scale=1.0, **kwargs):
    return MobileNetV3(_SMALL, _make_divisible(1024 * scale), scale,
                       **kwargs)
