"""Profiler (parity: python/paddle/profiler/ — ``Profiler`` context
manager with targets + wait/warmup/active scheduler, chrome-trace export,
``summary()`` tables; native side: host RecordEvent tracer + CUPTI device
tracer merged into one timeline).

TPU-native: the device tracer is XLA's — ``jax.profiler`` captures
XPlane/perfetto traces including every HLO op and ICI collective, which
covers both of the reference's tracers at once. This module adds the
paddle-shaped scheduler UX, ``RecordEvent`` host annotations (lowered to
jax.profiler.TraceAnnotation so they appear on the same timeline), and a
host-side op summary built from step timings.
"""

from __future__ import annotations

import contextlib
import os
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, List, Optional

import jax


class ProfilerTarget(Enum):
    CPU = "cpu"
    GPU = "gpu"  # accepted for parity; maps to the device tracer
    TPU = "tpu"


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int = 0, ready: int = 0, record: int = 1,
                   repeat: int = 0, skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Parity: paddle.profiler.make_scheduler(closed, ready, record)."""

    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


@dataclass
class _StepRecord:
    step: int
    ms: float
    annotations: List[str] = field(default_factory=list)


class RecordEvent:
    """Parity: paddle.profiler.RecordEvent — host-range annotation that
    lands on the XLA trace timeline."""

    def __init__(self, name: str):
        self.name = name
        self._ctx = None

    def __enter__(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)

    begin = __enter__

    def end(self):
        self.__exit__(None, None, None)


class Profiler:
    def __init__(
        self,
        targets=None,
        scheduler=None,
        on_trace_ready=None,
        log_dir: str = "./profiler_log",
        timer_only: bool = False,
    ):
        self.scheduler = scheduler or (lambda step: ProfilerState.RECORD)
        if isinstance(self.scheduler, tuple):
            lo, hi = self.scheduler
            self.scheduler = make_scheduler(
                closed=lo, ready=0, record=hi - lo
            )
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.on_trace_ready = on_trace_ready
        # an export_chrome_tracing callback names the dir the trace must
        # land in — repoint BEFORE any start_trace, not after the trace
        # was already written to the old dir
        export_dir = getattr(on_trace_ready, "_export_dir", None)
        if export_dir:
            self.log_dir = export_dir
        self.step_num = 0
        self._tracing = False
        self._records: List[_StepRecord] = []
        self._t0 = None

    # ------------------------------------------------------------------
    def start(self):
        self._maybe_transition()
        self._t0 = time.perf_counter()
        return self

    def stop(self):
        # record the final in-flight step: a run that ends between
        # step() calls would otherwise drop its last step from summary()
        if self._t0 is not None:
            self._records.append(
                _StepRecord(self.step_num,
                            (time.perf_counter() - self._t0) * 1e3)
            )
            self._t0 = None
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self.on_trace_ready:
                self.on_trace_ready(self)

    def step(self):
        if self._t0 is not None:
            self._records.append(
                _StepRecord(self.step_num,
                            (time.perf_counter() - self._t0) * 1e3)
            )
        self.step_num += 1
        self._maybe_transition()
        self._t0 = time.perf_counter()

    def _maybe_transition(self):
        state = self.scheduler(self.step_num)
        want_trace = state in (ProfilerState.RECORD,
                               ProfilerState.RECORD_AND_RETURN)
        if want_trace and not self._tracing and not self.timer_only:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._tracing = True
        elif not want_trace and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self.on_trace_ready:
                self.on_trace_ready(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------------
    def summary(self, sorted_by: str = "ms", op_top: int = 20) -> str:
        """Step-time table + per-op DEVICE-time table parsed from the
        exported trace (parity: paddle.profiler summary's operator/kernel
        views; see profiler.xplane)."""
        lines = []
        if not self._records:
            lines.append("no steps recorded")
        else:
            times = [r.ms for r in self._records]
            import numpy as np

            lines += [
                "step time summary (ms)",
                f"  steps: {len(times)}",
                f"  mean:  {np.mean(times):.2f}",
                f"  p50:   {np.percentile(times, 50):.2f}",
                f"  p90:   {np.percentile(times, 90):.2f}",
                f"  min:   {np.min(times):.2f}",
                f"  max:   {np.max(times):.2f}",
                f"  trace dir: {self.log_dir}",
            ]
        if not self.timer_only:
            from . import xplane

            try:
                ops = xplane.device_op_summary(self.log_dir)
            except Exception as e:  # a torn trace must not kill summary
                ops = None
                lines.append(f"(trace parse failed: {e!r})")
            if ops is not None:
                lines.append(xplane.format_summary(ops, top=op_top))
        return "\n".join(lines)


def export_chrome_tracing(dir_name: str):
    """Parity helper: the XLA trace is already perfetto/chrome-compatible;
    returns an on_trace_ready callback carrying the export dir. The
    ``_export_dir`` attribute lets Profiler repoint ``log_dir`` BEFORE
    ``start_trace`` (mutating it afterwards left the trace stranded in
    the old dir)."""

    def cb(prof: Profiler):
        prof.log_dir = dir_name

    cb._export_dir = dir_name
    return cb
