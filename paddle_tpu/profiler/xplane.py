"""Device-op summary tables from the XLA trace.

Parity: the reference's profiler statistics module
(paddle/fluid/platform/profiler/ — ``ChromeTracingLogger`` +
``StatisticsEngine`` building per-op/kernel device-time tables merged
from the host and CUPTI timelines) surfaced via
``paddle.profiler.Profiler.summary()``.

TPU-native: ``jax.profiler`` already merges host + device into one
exported trace (``*.trace.json.gz`` chrome format next to the
``.xplane.pb``). This module aggregates that trace's DEVICE plane events
into the tables the reference prints: per-op total device ms, count, %,
and a category rollup (matmul/conv vs collective vs copy vs other) —
the numbers MFU attribution needs ("what fraction of step time is
attention vs collectives").

CPU-backend traces carry no per-HLO-op device events (only runtime
threads), so there the summary degrades gracefully with a note.
"""

from __future__ import annotations

import glob
import re
import gzip
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_COLLECTIVE_MARKERS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "allreduce", "allgather", "collectivepermute",
    "send", "recv",
)
# "convolution", not "conv": the bare substring also matches dtype
# "convert" fusions, booking cast time under matmul/conv
_MATMUL_MARKERS = ("dot", "convolution", "matmul", "mxu", "gemm", "einsum")
_COPY_MARKERS = ("copy", "transpose", "reshape", "bitcast", "dynamic-slice",
                 "dynamic-update-slice", "concatenate", "pad", "slice")
_INFEED_MARKERS = ("infeed", "outfeed", "host-transfer")


# XLA trace events carry an ``hlo_category`` arg (e.g. "convolution
# fusion" for a fused GEMM, "custom-call" for a Pallas kernel); prefer
# it — name heuristics mislabel fusions ("bitcast_add_fusion" is a GEMM)
_CONTAINER_CATEGORIES = ("while", "conditional", "call")


def categorize(op_name: str, hlo_category: str = "",
               long_name: str = "") -> str:
    c = (hlo_category or "").lower()
    if c:
        if any(m in c for m in _COLLECTIVE_MARKERS):
            return "collective"
        if ("convolution" in c or "dot" in c or "matmul" in c
                or "einsum" in c):
            return "matmul/conv"
        if "custom-call" in c or "custom call" in c:
            return "custom-call (pallas)"
        if any(m in c for m in _INFEED_MARKERS):
            return "infeed/outfeed"
        if any(m in c for m in _COPY_MARKERS):
            return "copy/layout"
        if c != "fusion" and not c.endswith(" fusion"):
            # a real XLA category we have no bucket for (e.g.
            # "non-fusion elementwise") — surface it as-is; generic
            # fusion categories fall through to the name heuristics
            return c
    n = op_name.lower()
    # generic "fusion.N" events carry no signal in the NAME, but the
    # trace's long_name holds the fusion's HLO text: root shape +
    # operand names. The round-5 headline's whole 12.9% "other" bucket
    # decoded this way into AdamW master updates (operands named
    # %opt_state__master____...) and the embedding-grad scatter — all
    # HBM-roofline loop fusions worth naming, not hiding.
    if long_name and re.fullmatch(r"(wrapped_)?fusion[.\d]*", n):
        ln = long_name.lower()
        if "opt_state" in ln or "__master__" in ln:
            return "optimizer update"
        # scatter/gather only when the fusion's OWN computation says so
        # (calls=%scatter_computation / a root-level scatter(...) call).
        # Operand references (%gather.12 feeding a loop fusion, or an
        # %all-gather input in TP traces) must not claim the event.
        if re.search(r"(scatter|gather)_computation", ln) \
                or re.search(r"=\s*\S+\s+(scatter|gather)\(", ln):
            return "scatter/gather/slice"
    if any(m in n for m in _COLLECTIVE_MARKERS):
        return "collective"
    if any(m in n for m in _MATMUL_MARKERS):
        return "matmul/conv"
    if any(m in n for m in _INFEED_MARKERS):
        return "infeed/outfeed"
    # name the long tail (round-4 capture left 16.2% as one opaque
    # "other" bucket): XLA fusion names concatenate their root ops, so
    # substring heuristics attribute most of it. scatter/gather outranks
    # the copy markers ("dynamic-update-slice" is a cache write, not a
    # layout copy).
    if any(m in n for m in ("scatter", "gather", "dynamic-update",
                            "dynamic_update", "dynamic-slice",
                            "dynamic_slice")):
        return "scatter/gather/slice"
    if any(m in n for m in _COPY_MARKERS):
        return "copy/layout"
    if "rng" in n or "random" in n:
        return "rng"
    if "reduce" in n:
        return "reduce"
    if "transpose" in n or "reshape" in n:
        return "transpose/reshape"
    # short markers match whole NAME TOKENS only — substring matching
    # would book sort/xor/floor under elementwise via "or"
    tokens = set(re.split(r"[._\-0-9]+", n))
    if tokens & {"add", "mul", "multiply", "sub", "subtract", "div",
                 "divide", "exp", "tanh", "select", "convert", "compare",
                 "max", "maximum", "min", "minimum", "broadcast", "iota",
                 "clamp", "rsqrt", "log", "power", "and", "or", "not",
                 "sign", "loop"}:
        return "elementwise"
    return "other"


@dataclass
class OpRow:
    name: str
    total_ms: float
    count: int
    category: str

    @property
    def avg_ms(self) -> float:
        return self.total_ms / max(self.count, 1)


@dataclass
class DeviceOpSummary:
    plane: str
    rows: List[OpRow] = field(default_factory=list)
    n_planes: int = 1  # device planes aggregated (chips in the trace)

    @property
    def total_ms(self) -> float:
        return sum(r.total_ms for r in self.rows)

    def by_category(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for r in self.rows:
            out[r.category] = out.get(r.category, 0.0) + r.total_ms
        return out


def latest_trace_file(log_dir: str) -> Optional[str]:
    """Newest chrome-format trace under a jax.profiler log dir."""
    pattern = os.path.join(log_dir, "plugins", "profile", "*",
                           "*.trace.json.gz")
    files = glob.glob(pattern)
    return max(files, key=os.path.getmtime) if files else None


def parse_trace(path: str):
    """-> (process names {pid: name}, thread names {(pid,tid): name},
    complete events list)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    pids: Dict[int, str] = {}
    tids: Dict[tuple, str] = {}
    complete = []
    for e in events:
        ph = e.get("ph")
        if ph == "M":
            if e.get("name") == "process_name":
                pids[e["pid"]] = e.get("args", {}).get("name", "")
            elif e.get("name") == "thread_name":
                tids[(e["pid"], e.get("tid"))] = e.get(
                    "args", {}).get("name", "")
        elif ph == "X":
            complete.append(e)
    return pids, tids, complete


def device_op_summary(log_dir: str, top: int = 0
                      ) -> Optional[DeviceOpSummary]:
    """Aggregate the newest trace's device-plane op events.

    Device planes are processes named ``/device:TPU:N`` (or GPU). Within
    them, "XLA Ops"-style lines carry one complete event per executed HLO
    op with its device duration — the exact payload the reference reads
    from CUPTI. Returns None when no trace exists; a summary with empty
    rows when a trace exists but carries no device plane (CPU backend).
    """
    path = latest_trace_file(log_dir)
    if path is None:
        return None
    pids, tids, events = parse_trace(path)
    dev_pids = {p for p, name in pids.items()
                if name.startswith("/device:") and "CPU" not in name}
    if not dev_pids:
        return DeviceOpSummary(plane="(no device plane — CPU trace)")
    # prefer XLA-op lines; fall back to every line on the device plane
    op_keys = {k for k, name in tids.items()
               if k[0] in dev_pids and "xla op" in name.lower()}
    use_all = not op_keys
    agg: Dict[str, OpRow] = {}
    for e in events:
        pid = e.get("pid")
        if pid not in dev_pids:
            continue
        key = (pid, e.get("tid"))
        if not use_all and key not in op_keys:
            continue
        tname = tids.get(key, "").lower()
        if use_all and ("step" in tname or "framework" in tname):
            continue  # step markers duplicate the op time underneath
        name = e.get("name", "?")
        args = e.get("args") or {}
        hlo_cat = str(args.get("hlo_category", ""))
        # while/cond wrapper events cover their body ops, which appear
        # as separate events — counting both double-books the time
        if hlo_cat.lower() in _CONTAINER_CATEGORIES:
            continue
        dur_ms = float(e.get("dur", 0.0)) / 1e3  # chrome dur is in us
        row = agg.get(name)
        if row is None:
            agg[name] = OpRow(name, dur_ms, 1,
                              categorize(name, hlo_cat,
                                         str(args.get("long_name", ""))))
        else:
            row.total_ms += dur_ms
            row.count += 1
    rows = sorted(agg.values(), key=lambda r: -r.total_ms)
    if top:
        rows = rows[:top]
    plane = ", ".join(sorted(pids[p] for p in dev_pids))
    return DeviceOpSummary(plane=plane, rows=rows,
                           n_planes=len(dev_pids))


def format_summary(s: DeviceOpSummary, top: int = 20) -> str:
    if not s.rows:
        return f"device op summary: no device op events ({s.plane})"
    total = s.total_ms
    lines = [
        f"device op summary — plane {s.plane}, total {total:.3f} ms",
        f"{'op':48s} {'total ms':>10s} {'%':>6s} {'count':>7s} "
        f"{'avg ms':>9s}  category",
    ]
    for r in s.rows[:top]:
        pct = 100.0 * r.total_ms / total if total else 0.0
        name = r.name if len(r.name) <= 48 else r.name[:45] + "..."
        lines.append(
            f"{name:48s} {r.total_ms:10.3f} {pct:6.1f} {r.count:7d} "
            f"{r.avg_ms:9.4f}  {r.category}"
        )
    lines.append("category rollup:")
    for cat, ms in sorted(s.by_category().items(), key=lambda kv: -kv[1]):
        pct = 100.0 * ms / total if total else 0.0
        lines.append(f"  {cat:16s} {ms:10.3f} ms {pct:6.1f}%")
    return "\n".join(lines)
