"""Single-process save/load (parity: paddle.save / paddle.load,
python/paddle/framework/io.py).

Format: a directory-free single ``.npz``-in-pickle container — nested
python structures with jax arrays stored as numpy. Distributed sharded
checkpointing with cross-topology reshard-on-load lives in
``paddle_tpu.distributed.checkpoint``.
"""

from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np


def _to_host(obj):
    if isinstance(obj, jax.Array):
        return np.asarray(jax.device_get(obj))
    if isinstance(obj, dict):
        return {k: _to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_host(v) for v in obj)
    from ..core.parameter import Parameter

    if isinstance(obj, Parameter):
        return np.asarray(jax.device_get(obj.value))
    return obj


def save(obj, path):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_host(obj), f, protocol=4)


def load(path, return_numpy=False):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    if return_numpy:
        return obj

    def to_jax(o):
        if isinstance(o, np.ndarray):
            return jnp.asarray(o)
        if isinstance(o, dict):
            return {k: to_jax(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(to_jax(v) for v in o)
        return o

    return to_jax(obj)
