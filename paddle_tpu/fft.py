"""paddle.fft — discrete Fourier transforms.

Parity: ``paddle.fft`` (upstream: python/paddle/fft.py) — the full
fft/ifft/rfft/irfft/hfft/ihfft family in 1-D/2-D/N-D forms plus the
helper functions, with paddle's exact signatures: ``x`` (not numpy's
``a``) as the array argument, ``n``/``s`` length overrides, ``axis``/
``axes`` placement, and ``norm`` in {"backward", "ortho", "forward"}
(paddle's default "backward" == numpy/jnp's default None scaling).

TPU-native notes: everything lowers to XLA's FFT HLO (ducc on CPU,
the TPU FFT expansion on device); wrappers add paddle's argument
validation (positive lengths, known norm) and otherwise stay
zero-overhead pass-throughs, so there is no penalty versus calling
``jnp.fft`` directly inside jit.
"""

from __future__ import annotations

import jax.numpy as jnp

from .errors import InvalidArgumentError

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm is None:
        return "backward"
    if norm not in _NORMS:
        raise InvalidArgumentError(
            f"Unexpected norm: {norm!r}. Norm should be 'forward', "
            f"'backward' or 'ortho'")
    return norm


def _check_n(n):
    if n is not None and n <= 0:
        raise InvalidArgumentError(
            f"Invalid FFT argument n({n}), it should be positive.")
    return n


def _check_s(s):
    if s is not None:
        s = tuple(int(v) for v in s)
        if any(v <= 0 for v in s):
            raise InvalidArgumentError(
                f"Invalid FFT argument s({s}), all entries should be "
                "positive.")
    return s


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.fft(x, _check_n(n), axis, _check_norm(norm))


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.ifft(x, _check_n(n), axis, _check_norm(norm))


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.rfft(x, _check_n(n), axis, _check_norm(norm))


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.irfft(x, _check_n(n), axis, _check_norm(norm))


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.hfft(x, _check_n(n), axis, _check_norm(norm))


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return jnp.fft.ihfft(x, _check_n(n), axis, _check_norm(norm))


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.fft2(x, _check_s(s), axes, _check_norm(norm))


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.ifft2(x, _check_s(s), axes, _check_norm(norm))


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.rfft2(x, _check_s(s), axes, _check_norm(norm))


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return jnp.fft.irfft2(x, _check_s(s), axes, _check_norm(norm))


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.fftn(x, _check_s(s), axes, _check_norm(norm))


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.ifftn(x, _check_s(s), axes, _check_norm(norm))


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.rfftn(x, _check_s(s), axes, _check_norm(norm))


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return jnp.fft.irfftn(x, _check_s(s), axes, _check_norm(norm))


def fftfreq(n, d=1.0, dtype=None, name=None):
    _check_n(n)
    out = jnp.fft.fftfreq(n, d)
    return out.astype(dtype) if dtype is not None else out


def rfftfreq(n, d=1.0, dtype=None, name=None):
    _check_n(n)
    out = jnp.fft.rfftfreq(n, d)
    return out.astype(dtype) if dtype is not None else out


def fftshift(x, axes=None, name=None):
    return jnp.fft.fftshift(x, axes)


def ifftshift(x, axes=None, name=None):
    return jnp.fft.ifftshift(x, axes)
