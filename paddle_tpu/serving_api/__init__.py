"""Streaming serving front door: OpenAI-compatible SSE HTTP API +
SLO-aware multi-tenant admission scheduling over the
continuous-batching engine (single engine or ``EngineRouter`` fleet).

Quickstart::

    from paddle_tpu.inference.serving import (
        ContinuousBatchingEngine, EngineConfig)
    from paddle_tpu.serving_api import (
        SLOFairScheduler, TenantQuota, start_api_server)

    eng = ContinuousBatchingEngine(model, EngineConfig(paged=True))
    srv = start_api_server(
        eng, scheduler=SLOFairScheduler(
            tenants={"acme": TenantQuota(weight=2.0, max_slots=3)}))
    # POST {srv.url}/v1/completions  {"prompt": [3,7,11], "stream": true}
    srv.shutdown()

See README "Serving front door" for the endpoint table, request
schema and scheduler/quota flags.
"""

from .protocol import (
    CompletionRequest,
    ProtocolError,
    parse_completion_request,
)
from .scheduler import SLOFairScheduler, TenantQuota, default_scheduler
from .server import ServingAPIServer, ServingFrontDoor, start_api_server

__all__ = [
    "CompletionRequest",
    "ProtocolError",
    "parse_completion_request",
    "SLOFairScheduler",
    "TenantQuota",
    "default_scheduler",
    "ServingAPIServer",
    "ServingFrontDoor",
    "start_api_server",
]
