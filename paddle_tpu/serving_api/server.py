"""Async streaming HTTP front door for the continuous-batching engine.

The subsystem that turns the engine from a benchmark-driven library
into a served product: a stdlib-only threaded HTTP server exposing
OpenAI-compatible ``/v1/completions`` (server-sent-event token
streaming) and ``/v1/models``, riding the SAME observability surface
as :func:`~paddle_tpu.inference.serving.start_metrics_server`
(``/metrics``, ``/healthz``, ``/trace``, ``/timeline`` — one routing
function, not a copy).

Threading model — the engine's single-scheduler-thread contract is
kept, not worked around:

* ONE **driver thread** owns the engine (or ``EngineRouter``): it
  ticks ``step_chunk`` (chunk length chosen by the scheduler policy),
  applies deferred cancels, and flushes newly-accepted tokens into
  per-request stream queues. It is the only thread that touches
  scheduler state — exactly what the sanitizer's thread-ownership
  invariant enforces.
* HTTP **handler threads** are producers/consumers only: they submit
  via ``add_request`` (the documented producer-safe entry), then block
  on their stream queue. Tokens stream out as the engine ACCEPTS them
  — spec-decode's multi-token commits arrive as multi-token SSE
  deltas, the user-visible form of that latency win.
* A client disconnect mid-stream surfaces as a failed socket write in
  the handler, which defers ``cancel(rid)`` to the driver thread —
  slots, KV pages and prefix refs are provably freed through the
  engine's one teardown path (the chaos lane's disconnect storm pins
  this).

Zero new compiled programs: the front door is transport + policy; the
compile-counter guard pins the program set unchanged.
"""

from __future__ import annotations

import collections
import itertools
import json
import queue
import threading
from typing import Dict, Optional

from .. import flags
from ..inference.router import EngineRouter
from ..inference.serving import metrics_http_get
from . import protocol
from .scheduler import default_scheduler

# sentinel kinds on a stream queue
_TOKENS, _DONE, _ERROR = "tokens", "done", "error"


class _Stream:
    """Bridge between the driver thread (producer) and one handler
    thread (consumer): a queue of token deltas ending in a terminal
    sentinel. ``sent`` is driver-private (how much of ``req.output``
    has been flushed)."""

    __slots__ = ("q", "sent", "closed")

    def __init__(self):
        self.q: "queue.Queue" = queue.Queue()
        self.sent = 0
        self.closed = False

    def push_tokens(self, toks):
        self.q.put((_TOKENS, toks))

    def finish(self, reason: Optional[str], meta: dict):
        self.q.put((_DONE, reason, meta))

    def error(self, message: str):
        self.q.put((_ERROR, message))


class ServingFrontDoor:
    """Owns the driver thread and the rid→stream registry. Fronts a
    single :class:`ContinuousBatchingEngine` or an
    :class:`~paddle_tpu.inference.router.EngineRouter` fleet — the
    submit/cancel/result surface is shape-compatible."""

    def __init__(self, target, scheduler=None, max_chunk: int = 8,
                 model_id: str = "paddle-tpu"):
        self.target = target
        self.model_id = model_id
        self.max_chunk = int(max_chunk)
        self._is_router = isinstance(target, EngineRouter)
        self._sched = scheduler
        if scheduler is not None:
            if self._is_router:
                # one policy instance across the fleet: the fair-share
                # ledger is fleet-global (tenants span replicas)
                for rep in target._replicas:
                    rep.engine.set_scheduler(scheduler)
            else:
                target.set_scheduler(scheduler)
        self._streams: Dict[int, _Stream] = {}
        self._streams_lock = threading.Lock()
        # distinct tenant ids admitted so far: tenant strings are
        # CLIENT-controlled and each unique value mints permanent
        # per-tenant series/buckets — bounded by PT_FLAGS_api_max_
        # tenants (new tenants past the cap are rejected 429). The
        # lock makes check+reserve atomic across handler threads; a
        # reservation rolls back if the request never admits, so
        # junk requests can't burn the cap
        self._tenants_seen: set = set()
        self._tenant_lock = threading.Lock()
        # cancels deferred to the driver thread (engine.cancel frees
        # slots/pages — scheduler-thread-only, per the engine contract)
        self._cancels: "collections.deque" = collections.deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._dead: Optional[str] = None
        self._req_seq = itertools.count()
        self._thread = threading.Thread(
            target=self._drive, daemon=True, name="pt-api-driver")
        self._thread.start()

    # ---------------- handler-thread surface ----------------
    def submit(self, creq: "protocol.CompletionRequest"):
        """Validate+enqueue one completion request; returns
        ``(rid, stream)``. Runs on a handler thread — ``add_request``
        is the documented producer-safe entry; the stream registers
        AFTER submit and catches up from ``output[0]``, so no token
        can be lost in the window."""
        if self._dead is not None:
            raise RuntimeError(f"serving driver died: {self._dead}")
        reserved = False
        if creq.tenant is not None:
            with self._tenant_lock:
                if creq.tenant not in self._tenants_seen:
                    cap = int(flags.flag("api_max_tenants"))
                    if len(self._tenants_seen) >= cap:
                        raise protocol.ProtocolError(
                            429, f"tenant cardinality cap reached "
                            f"({cap} distinct tenants; "
                            "PT_FLAGS_api_max_tenants) — new tenant "
                            "ids are rejected to bound per-tenant "
                            "metric/accounting state")
                    self._tenants_seen.add(creq.tenant)
                    reserved = True
        try:
            rid = self.target.add_request(creq.prompt,
                                          **creq.engine_kwargs())
        except BaseException:
            if reserved:
                # the request never admitted: a junk request must not
                # burn a cap slot (the guard would become the DoS)
                with self._tenant_lock:
                    self._tenants_seen.discard(creq.tenant)
            raise
        stream = _Stream()
        with self._streams_lock:
            self._streams[rid] = stream
        self._wake.set()
        return rid, stream

    def defer_cancel(self, rid: int):
        """Request cancellation from a handler thread (client
        disconnect): applied by the driver at the next tick."""
        self._cancels.append(rid)
        self._wake.set()

    # ---------------- driver thread ----------------
    def _tick(self) -> bool:
        k = self.max_chunk
        if self._sched is not None:
            if self._is_router:
                # the fleet tick drives every replica with ONE chunk
                # length: any replica with urgent admission work (or
                # a router-held request) pulls the whole tick down to
                # the probe chunk — a full chunk anywhere delays that
                # replica's next admission point
                k = min(self._sched.chunk_len(rep.engine,
                                              self.max_chunk)
                        for rep in self.target._replicas)
                if self.target._queue:
                    k = min(k, getattr(self._sched, "probe_chunk", k))
            else:
                k = self._sched.chunk_len(self.target, self.max_chunk)
        if self._is_router:
            return self.target.step(max_chunk=k)
        return self.target.step_chunk(k)

    def _request_index(self) -> Dict[int, object]:
        """rid → live/finished Request, built ONCE per flush — driver
        thread only (the structures are scheduler-owned). One pass
        over queues/slots/finish registries per tick keeps the flush
        O(streams), the same order as the engine's own per-tick queue
        scans; per-stream linear hunts would make the hot loop
        O(streams × queue). Failover moves a rid between replicas;
        rebuilding per tick follows it for free."""
        idx: Dict[int, object] = {}
        if self._is_router:
            engines = [rep.engine for rep in self.target._replicas]
            for req in list(self.target._queue):
                idx[req.rid] = req
            idx.update(self.target._finished)
        else:
            engines = [self.target]
        for eng in engines:
            for req in list(eng._queue):
                idx[req.rid] = req
            for req in list(eng._slot_req.values()):
                idx[req.rid] = req
            idx.update(eng._finished)
        return idx

    def _flush_streams(self):
        with self._streams_lock:
            items = list(self._streams.items())
        if not items:
            return
        index = self._request_index()
        for rid, st in items:
            req = index.get(rid)
            if req is None:
                continue
            out = req.output
            if len(out) > st.sent:
                st.push_tokens([int(t) for t in out[st.sent:]])
                st.sent = len(out)
            if req.done:
                st.finish(req.finish_reason, {
                    "prompt_tokens": int(req.prompt.size),
                    "completion_tokens": len(out),
                    "ttft_ms": req.ttft_ms,
                    "tpot_ms": req.tpot_ms,
                    "slo_met": req.slo_met,
                })
                with self._streams_lock:
                    self._streams.pop(rid, None)
                # REAP: the library path's finish registry assumes a
                # caller harvests results and discards the engine; a
                # long-running server must not retain every served
                # request's prompt/output forever (cumulative
                # tenant/SLO/cost accounting already landed at finish)
                self._reap(rid)

    def _reap(self, rid: int):
        """Drop a delivered request's terminal record (driver thread
        only — the registries are scheduler-owned)."""
        if self._is_router:
            self.target._finished.pop(rid, None)
            ridx = self.target._owner.pop(rid, None)
            if ridx is not None:
                self.target._replicas[ridx].engine._finished.pop(
                    rid, None)
        else:
            self.target._finished.pop(rid, None)

    def _apply_cancels(self):
        while self._cancels:
            try:
                rid = self._cancels.popleft()
            except IndexError:
                break
            self.target.cancel(rid)
            # the cancel path marks req.done — the normal flush
            # delivers the terminal sentinel to any waiting handler

    def _drive(self):
        try:
            while not self._stop.is_set():
                self._apply_cancels()
                busy = self._tick()
                self._flush_streams()
                if not busy and not self._cancels:
                    # idle: sleep until a submit/cancel wakes us (the
                    # timeout keeps deadline expiry ticking for queued
                    # requests even with no new arrivals)
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
        except BaseException as e:  # noqa: BLE001
            self._dead = f"{type(e).__name__}: {e}"
            with self._streams_lock:
                streams, self._streams = dict(self._streams), {}
            for st in streams.values():
                st.error(self._dead)
            raise

    def shutdown(self):
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
        with self._streams_lock:
            streams, self._streams = dict(self._streams), {}
        for st in streams.values():
            st.error("server shutting down")


class ServingAPIServer:
    """Handle for a running front door: ``url`` for the bound port,
    clean idempotent ``shutdown()`` (driver joined, listener closed) —
    the :class:`~paddle_tpu.inference.serving.MetricsServer` contract,
    so chaos tests and multi-server runs never leak threads or fds."""

    def __init__(self, server, thread, front_door):
        self._server = server
        self._thread = thread
        self.front_door = front_door
        self._closed = False

    @property
    def server_address(self):
        return self._server.server_address

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        self.front_door.shutdown()
        self._server.shutdown()
        self._thread.join(timeout=10)
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


def start_api_server(target, host: str = "127.0.0.1", port: int = 0,
                     scheduler="auto", max_chunk: int = 8,
                     model_id: str = "paddle-tpu"):
    """Serve the OpenAI-compatible streaming API over ``target`` (an
    engine or an :class:`EngineRouter`) on a daemon thread pool.

    Endpoints: ``POST /v1/completions`` (SSE streaming with
    ``"stream": true``, aggregate JSON otherwise), ``GET /v1/models``,
    plus the full observability surface (``/metrics``, ``/healthz``,
    ``/trace``, ``/timeline``) via the same routing the metrics server
    uses.

    ``scheduler``: an admission policy object (installed via
    ``engine.set_scheduler``), ``None`` for engine-native FIFO, or
    ``"auto"`` (default) to build from ``PT_FLAGS_sched_policy``.
    Returns a :class:`ServingAPIServer` handle (``handle.url``,
    ``handle.shutdown()``; also a context manager)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if scheduler == "auto":
        scheduler = default_scheduler()
    fd = ServingFrontDoor(target, scheduler=scheduler,
                          max_chunk=max_chunk, model_id=model_id)

    class _Handler(BaseHTTPRequestHandler):
        def _send(self, code, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code, obj):
            self._send(code, json.dumps(obj, default=str).encode(),
                       "application/json")

        def log_message(self, fmt, *args):  # quiet request noise
            pass

        def do_GET(self):
            try:
                if self.path.split("?")[0] == "/v1/models":
                    self._send_json(
                        200, protocol.models_payload(fd.model_id))
                    return
                routed = metrics_http_get(fd.target, self.path)
                if routed is None:
                    self._send(404, protocol.error_body(
                        "not found", "not_found_error"),
                        "application/json")
                else:
                    self._send(*routed)
            except BrokenPipeError:
                pass
            except Exception as e:  # noqa: BLE001
                try:
                    self._send(500, protocol.error_body(
                        repr(e), "internal_error"), "application/json")
                except Exception:
                    pass

        # ---------------- completions ----------------
        def do_POST(self):
            try:
                if self.path.split("?")[0] != "/v1/completions":
                    self._send(404, protocol.error_body(
                        "not found", "not_found_error"),
                        "application/json")
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, TypeError) as e:
                    self._send(400, protocol.error_body(
                        f"invalid JSON body: {e}"), "application/json")
                    return
                try:
                    creq = protocol.parse_completion_request(body)
                    rid, stream = fd.submit(creq)
                except protocol.ProtocolError as e:
                    self._send(e.status, protocol.error_body(str(e)),
                               "application/json")
                    return
                except ValueError as e:
                    # build_request's validation — the same errors the
                    # library path raises, mapped to 400
                    self._send(400, protocol.error_body(str(e)),
                               "application/json")
                    return
                if creq.stream:
                    self._stream_response(creq, rid, stream)
                else:
                    self._aggregate_response(creq, rid, stream)
            except (BrokenPipeError, ConnectionResetError):
                pass
            except Exception as e:  # noqa: BLE001
                try:
                    self._send(500, protocol.error_body(
                        repr(e), "internal_error"), "application/json")
                except Exception:
                    pass

        def _wait(self, stream):
            """Next stream item; surfaces a driver death instead of
            blocking forever."""
            while True:
                try:
                    return stream.q.get(timeout=30.0)
                except queue.Empty:
                    if fd._dead is not None:
                        return (_ERROR, fd._dead)
                    # otherwise keep waiting: the engine enforces
                    # request deadlines and will close the stream

        def _stream_response(self, creq, rid, stream):
            cid = f"cmpl-{rid}"
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            try:
                if creq.echo:
                    self.wfile.write(protocol.sse_data(
                        protocol.completion_chunk(
                            cid, fd.model_id,
                            [int(t) for t in creq.prompt])))
                    self.wfile.flush()
                while True:
                    item = self._wait(stream)
                    if item[0] == _TOKENS:
                        self.wfile.write(protocol.sse_data(
                            protocol.completion_chunk(
                                cid, fd.model_id, item[1])))
                        self.wfile.flush()
                    elif item[0] == _DONE:
                        self.wfile.write(protocol.sse_data(
                            protocol.completion_chunk(
                                cid, fd.model_id, [],
                                finish_reason=item[1])))
                        self.wfile.write(protocol.SSE_DONE)
                        self.wfile.flush()
                        return
                    else:  # _ERROR
                        self.wfile.write(protocol.sse_data(
                            {"error": {"message": item[1],
                                       "type": "internal_error"}}))
                        self.wfile.flush()
                        return
            except (BrokenPipeError, ConnectionResetError, OSError):
                # CLIENT DISCONNECT mid-stream: the engine must get
                # its slot/pages/prefix refs back — cancel on the
                # driver (scheduler) thread, never from here
                fd.defer_cancel(rid)

        def _aggregate_response(self, creq, rid, stream):
            cid = f"cmpl-{rid}"
            tokens = []
            reason = None
            while True:
                item = self._wait(stream)
                if item[0] == _TOKENS:
                    tokens.extend(item[1])
                elif item[0] == _DONE:
                    reason = item[1]
                    meta = item[2]
                    break
                else:
                    self._send(500, protocol.error_body(
                        item[1], "internal_error"), "application/json")
                    return
            try:
                self._send_json(200, protocol.completion_response(
                    cid, fd.model_id, tokens, reason,
                    meta["prompt_tokens"],
                    echo_tokens=([int(t) for t in creq.prompt]
                                 if creq.echo else None)))
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # request already finished engine-side: no leak

    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="pt-api-server")
    thread.start()
    return ServingAPIServer(server, thread, fd)
