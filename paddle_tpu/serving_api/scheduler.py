"""SLO-aware multi-tenant admission scheduler for the serving engine.

Replaces FIFO admission with a three-tier policy the goodput-under-SLO
sweep ranks directly (the metric PR 6 built for exactly this):

1. **Deadline urgency** — a queued SLO-tracked request whose remaining
   TTFT budget has shrunk below the margin jumps the queue (most
   urgent first). FIFO's failure mode is interactive requests timing
   out behind a wall of batch prefills; this tier is the fix.
2. **Weighted fair share** — otherwise, tenants are served in order of
   accumulated virtual service (admitted tokens / weight), the classic
   WFQ discipline: a tenant flooding the queue only raises its own
   virtual time, so a light tenant's next request always ranks ahead.
   New tenants join at the current minimum (no banked credit).
3. **Target tightness, then FIFO** — within a tenant, tighter TTFT
   targets first; final tie-break is submission order.

Per-tenant **quotas** (max slots / max KV pages) bound what any tenant
can occupy regardless of queue pressure, and **preemption**
(``PT_FLAGS_sched_preempt``) lets an about-to-miss interactive request
evict a batch-class slot: the victim re-queues WITH its generated
history and replays through the existing ``[slots, C]`` chunked
prefill program — the crash-recovery machinery, so greedy outputs stay
bit-identical and ZERO new programs compile.

The chunk-split levers: ``chunk_len`` shrinks the decode chunk to the
probe length while urgent admissions wait (the step's token budget is
spent reaching the next admission point sooner instead of on
incumbents — the PR-5 load-curve knob, now SLO-driven), and
``slot_caps`` bounds batch-class slots' per-chunk COMMIT budget while
urgent work queues (their emission and paged page-growth, not the
chunk's device time — the fixed-shape program computes every slot's
rows regardless).

Everything here is host-side policy consulted on the scheduler thread
(``engine.set_scheduler`` documents the seam): no compiled program is
touched, and per-request greedy outputs are bit-identical under any
admission order — only TTFT/goodput move, which is the point.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from .. import flags

# most-recent preempted rids remembered (see SLOFairScheduler._preempts)
_PREEMPT_LEDGER_CAP = 4096


@dataclass
class TenantQuota:
    """Per-tenant scheduling config: ``weight`` is the fair-share
    ratio (2.0 = twice the service of a weight-1 tenant); ``max_slots``
    / ``max_pages`` cap what the tenant may OCCUPY at once (None =
    uncapped). Quotas gate admission only — in-flight requests always
    run to completion (or preemption)."""

    weight: float = 1.0
    max_slots: Optional[int] = None
    max_pages: Optional[int] = None

    def __post_init__(self):
        if not self.weight > 0:
            raise ValueError(
                f"TenantQuota.weight must be > 0; got {self.weight}")
        for name in ("max_slots", "max_pages"):
            v = getattr(self, name)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, int) or v < 1):
                raise ValueError(
                    f"TenantQuota.{name} must be a positive int or "
                    f"None; got {v!r}")


class SLOFairScheduler:
    """The shipped scheduler policy (see module docstring). Install
    with ``engine.set_scheduler(SLOFairScheduler(...))`` — or let the
    front door build one via ``PT_FLAGS_sched_policy=slo_fair``.

    One instance may front several engines (an ``EngineRouter``
    fleet): the fair-share ledger is then fleet-global, which is the
    honest reading of "tenant share" when tenants span replicas.
    """

    name = "slo_fair"

    def __init__(self, tenants: Optional[Dict[str, TenantQuota]] = None,
                 default_weight: float = 1.0,
                 ttft_margin_ms: float = 50.0,
                 probe_chunk: int = 2,
                 preempt: Optional[bool] = None,
                 max_preemptions_per_request: int = 1):
        if not default_weight > 0:
            raise ValueError(
                f"default_weight must be > 0; got {default_weight}")
        if ttft_margin_ms < 0:
            raise ValueError(
                f"ttft_margin_ms must be >= 0; got {ttft_margin_ms}")
        if probe_chunk < 1:
            raise ValueError(
                f"probe_chunk must be >= 1; got {probe_chunk}")
        self.tenants: Dict[str, TenantQuota] = dict(tenants or {})
        self.default_weight = float(default_weight)
        self.ttft_margin_ms = float(ttft_margin_ms)
        self.probe_chunk = int(probe_chunk)
        self.max_preemptions_per_request = int(
            max_preemptions_per_request)
        self.preempt_enabled = (bool(flags.flag("sched_preempt"))
                                if preempt is None else bool(preempt))
        # tenant -> accumulated virtual service (admitted tokens /
        # weight); relative order is all that matters, so the ledger
        # only ever grows — newcomers join at the current minimum
        self._service: Dict[str, float] = {}
        # rid -> preemptions consumed (progress bound: past the cap a
        # request can never be evicted again). Bounded FIFO: rids are
        # minted monotonically and never reused, so on a long-lived
        # server old entries are dead weight — the ledger keeps the
        # most recent _PREEMPT_LEDGER_CAP rids (a dropped entry could
        # at worst let an ancient still-running request be preempted
        # one extra time — bounded harm, not a leak)
        self._preempts: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()

    # ---------------- fair-share ledger ----------------
    def _weight(self, tenant: Optional[str]) -> float:
        q = self.tenants.get(tenant or "-")
        return q.weight if q is not None else self.default_weight

    def _service_of(self, tenant: Optional[str]) -> float:
        key = tenant or "-"
        svc = self._service.get(key)
        if svc is None:
            # join at the current minimum: a tenant that sat out an
            # hour must not bank an hour of credit against the rest
            svc = self._service[key] = min(
                self._service.values(), default=0.0)
        return svc

    def note_admit(self, engine, req):
        """A pick's claim committed: charge the tenant's virtual
        service with the request's token cost (prompt + budget — the
        admission-time estimate of what the slot will spend). A
        RE-admission (preemption/crash-replay re-queue: the request
        carries output or retries) is not charged again — the tenant
        already paid for this request's service once, and billing the
        preemption VICTIM twice would compound its penalty."""
        del engine
        if req.output or req._retries:
            return
        key = req.tenant or "-"
        cost = (int(req.prompt.size) + int(req.max_new_tokens)) \
            / self._weight(req.tenant)
        self._service[key] = self._service_of(req.tenant) + cost

    # ---------------- urgency ----------------
    @staticmethod
    def _ttft_slack_ms(req, now: float) -> Optional[float]:
        """Remaining TTFT budget (ms); None for target-less requests.
        Already-admitted requests (replay/preempted, ttft stamped)
        keep their original clock — the slack is vs FIRST submission,
        the same honesty rule the SLO accounting follows."""
        if req.ttft_target_ms is None or req.ttft_ms is not None:
            return None
        return req.ttft_target_ms - (now - req._submit_t) * 1e3

    def _at_risk(self, req, now: float) -> bool:
        slack = self._ttft_slack_ms(req, now)
        return slack is not None and slack <= self.ttft_margin_ms

    def _queued_at_risk(self, engine, now: float) -> bool:
        """An ADMISSIBLE at-risk request is queued: quota-blocked
        urgency must not trigger the chunk-split levers — the levers
        would tax every other tenant while the request they serve can
        never be placed."""
        usage = self._usage_map(engine)
        return any(self._at_risk(r, now)
                   and self.quota_ok(engine, r, usage)
                   for r in list(engine._queue))

    # ---------------- quotas ----------------
    def _usage_map(self, engine) -> Dict[str, list]:
        """tenant -> [active slots, held pages], computed ONCE per
        hook call (a per-candidate recount would make a deep queue's
        pick O(queue x slots)) — read on the scheduler thread, where
        the slot map is stable."""
        usage: Dict[str, list] = {}
        for slot, req in list(engine._slot_req.items()):
            u = usage.setdefault(req.tenant or "-", [0, 0])
            u[0] += 1
            if engine.pool is not None:
                u[1] += len(engine.pool.pages_of[slot])
        return usage

    def quota_ok(self, engine, req, usage=None) -> bool:
        q = self.tenants.get(req.tenant or "-")
        if q is None or (q.max_slots is None and q.max_pages is None):
            return True
        if usage is None:
            usage = self._usage_map(engine)
        slots, pages = usage.get(req.tenant or "-", (0, 0))
        if q.max_slots is not None and slots >= q.max_slots:
            return False
        if q.max_pages is not None and engine.pool is not None \
                and pages >= q.max_pages:
            return False
        return True

    # ---------------- the engine's policy hooks ----------------
    def pick(self, engine, candidates):
        """Admission order (``engine._pick_admission``): the best
        admissible queued request, or None when every candidate is
        quota-blocked."""
        now = time.perf_counter()
        usage = self._usage_map(engine)
        best = None
        best_key = None
        for i, req in enumerate(candidates):
            if not self.quota_ok(engine, req, usage):
                continue
            slack = self._ttft_slack_ms(req, now)
            if slack is not None and slack <= self.ttft_margin_ms:
                key = (0, slack, i)
            else:
                key = (1, self._service_of(req.tenant),
                       req.ttft_target_ms
                       if req.ttft_target_ms is not None
                       else float("inf"), i)
            if best_key is None or key < best_key:
                best, best_key = req, key
        return best

    def before_admission(self, engine):
        """The preemption window: when no slot is free and an
        at-risk, quota-clean request waits, evict the cheapest
        batch-class victim (fewest generated tokens = least replay
        recompute). Returns the preempted rids — the engine excludes
        them from this wave, so the freed slot goes to the urgent
        request, not back to the victim."""
        if not self.preempt_enabled:
            return ()
        if engine._free_heap and not engine._pool_blocked_prev:
            # slots available AND the last admission pass didn't
            # block on KV-pool pages — nothing to evict for. (The
            # pool-blocked case is the PAGED engine's dominant
            # saturation mode: slots free, pages exhausted —
            # preempting a page-holding batch victim frees exactly
            # what the urgent request needs.)
            return ()
        if engine._draining:
            # the admission loop refuses FRESH requests while
            # draining — preempting a victim for one would discard
            # its in-flight chunk and pay full replay for a slot
            # nothing can claim
            return ()
        now = time.perf_counter()
        usage = self._usage_map(engine)
        urgent = next(
            (r for r in list(engine._queue)
             if self._at_risk(r, now)
             and self.quota_ok(engine, r, usage)),
            None)
        if urgent is None:
            return ()
        victim_slot = None
        victim_key = None
        for slot, req in list(engine._slot_req.items()):
            if req.slo != "batch":
                continue  # only batch-class slots are evictable
            if self._preempts.get(req.rid, 0) \
                    >= self.max_preemptions_per_request:
                continue
            key = (len(req.output), slot)
            if victim_key is None or key < victim_key:
                victim_slot, victim_key = slot, key
        if victim_slot is None:
            return ()
        victim = engine._slot_req[victim_slot]
        if not engine.preempt(victim_slot):
            return ()
        self._preempts[victim.rid] = \
            self._preempts.get(victim.rid, 0) + 1
        self._preempts.move_to_end(victim.rid)
        while len(self._preempts) > _PREEMPT_LEDGER_CAP:
            self._preempts.popitem(last=False)
        return (victim.rid,)

    def slot_caps(self, engine) -> Optional[np.ndarray]:
        """Per-slot chunk-budget caps (``engine._slot_budgets``):
        while an at-risk request waits in the queue, batch-class
        slots commit at most ``probe_chunk`` tokens per chunk —
        bounding their emission and paged page-growth while the
        scheduler works to place urgent traffic. None = uncapped
        (the common case: no urgent work queued)."""
        if not engine._queue:
            return None
        now = time.perf_counter()
        if not self._queued_at_risk(engine, now):
            return None
        caps = np.full((engine.cfg.max_slots,),
                       np.iinfo(np.int32).max, np.int32)
        for slot, req in list(engine._slot_req.items()):
            if req.slo == "batch":
                caps[slot] = self.probe_chunk
        return caps

    def chunk_len(self, engine, max_chunk: int) -> int:
        """Decode-chunk length for the next tick: drop to the probe
        chunk only while admission work is queued AND admission can
        happen SOON — a free slot now, or an active slot whose
        remaining budget ends inside this chunk (``step_adaptive``'s
        measured discipline: a full chunk spends K tokens per
        incumbent before the next admission point, but when every
        slot is busy with long budgets a short chunk buys nothing and
        costs a host sync per boundary). Only two distinct K values
        ever dispatch, so at most two decode programs compile for the
        engine's lifetime."""
        if not engine._queue:
            return max_chunk
        if not engine.active.all():
            return min(self.probe_chunk, max_chunk)
        # raw remaining budgets (not _slot_budgets: our own slot_caps
        # would masquerade capped slots as about-to-finish)
        soonest = min(
            (min(req.max_new_tokens - len(req.output),
                 engine.cfg.max_len - 1 - int(engine.seq_lens[slot]))
             for slot, req in list(engine._slot_req.items())),
            default=max_chunk + 1)
        if soonest <= max_chunk:
            return min(self.probe_chunk, max_chunk)
        return max_chunk

    def snapshot(self) -> dict:
        """Host-side policy state (copy-on-read): the fair-share
        ledger and preemption ledger sizes."""
        return {
            "policy": self.name,
            "preempt_enabled": self.preempt_enabled,
            "service": {k: v for k, v in list(self._service.items())},
            "preempted_requests": len(self._preempts),
            "tenants": {
                k: {"weight": q.weight, "max_slots": q.max_slots,
                    "max_pages": q.max_pages}
                for k, q in list(self.tenants.items())},
        }


def default_scheduler():
    """The front door's default policy, from ``PT_FLAGS_sched_policy``:
    ``"fifo"`` → None (the engine's native submission-order
    admission), ``"slo_fair"`` → a default-config
    :class:`SLOFairScheduler`."""
    policy = str(flags.flag("sched_policy")).lower()
    if policy == "fifo":
        return None
    if policy == "slo_fair":
        return SLOFairScheduler()
    raise ValueError(
        f"PT_FLAGS_sched_policy must be fifo|slo_fair; got {policy!r}")
