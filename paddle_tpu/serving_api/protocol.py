"""OpenAI-compatible wire shapes for the streaming serving front door.

The front door speaks the ``/v1/completions`` request/response shape so
standard load generators and client SDKs can drive the engine. One
deliberate deviation: the repo ships no tokenizer, so ``prompt`` is a
TOKEN-ID array (``[3, 7, 11]``) — the convention serving load
generators use when benchmarking token-level engines — and every
response carries the generated ids in ``choices[0].token_ids`` next to
a space-joined ``text`` rendering. Everything else follows the spec:
SSE chunks are ``data: {json}\\n\\n`` frames ending in
``data: [DONE]``, errors are ``{"error": {"message", "type"}}``.

Pure parsing/formatting — no engine imports, no threads, so the
request-validation tests run without building a model.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np


class ProtocolError(ValueError):
    """A malformed request: ``status`` is the HTTP code to return."""

    def __init__(self, status: int, message: str):
        self.status = int(status)
        super().__init__(message)


# request fields the parser understands; anything else is rejected
# loudly (a silently-ignored "max_new_tokens" typo would serve 16
# tokens and leave the caller debugging the wrong layer)
_KNOWN_FIELDS = {
    "model", "prompt", "max_tokens", "stream", "temperature", "top_k",
    "top_p", "greedy", "eos_token_id", "stop", "tenant", "slo",
    "ttft_target_ms", "tpot_target_ms", "deadline_ms", "user", "n",
    "echo",
}


@dataclass
class CompletionRequest:
    """A validated ``/v1/completions`` body, ready to map onto
    ``engine.add_request`` keyword-for-keyword."""

    prompt: np.ndarray = field(default_factory=lambda: np.zeros(0))
    max_tokens: int = 16
    stream: bool = False
    echo: bool = False
    model: str = ""
    tenant: Optional[str] = None
    slo: Optional[str] = None
    ttft_target_ms: Optional[float] = None
    tpot_target_ms: Optional[float] = None
    deadline_ms: Optional[float] = None
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    greedy: Optional[bool] = None
    eos_token_id: Optional[int] = None

    def engine_kwargs(self) -> dict:
        """The ``add_request`` keywords this request carries (transport
        fields — stream/echo/model — stay behind)."""
        return {
            "max_new_tokens": self.max_tokens,
            "eos_token_id": self.eos_token_id,
            "temperature": self.temperature,
            "top_k": self.top_k,
            "top_p": self.top_p,
            "greedy": self.greedy,
            "tenant": self.tenant,
            "slo": self.slo,
            "ttft_target_ms": self.ttft_target_ms,
            "tpot_target_ms": self.tpot_target_ms,
            "deadline_ms": self.deadline_ms,
        }


def _opt_num(body: dict, key: str, kind=float):
    val = body.get(key)
    if val is None:
        return None
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        raise ProtocolError(400, f"{key} must be a number; got {val!r}")
    return kind(val)


def parse_completion_request(body) -> CompletionRequest:
    """Validate a decoded ``/v1/completions`` JSON body. Shape errors
    raise :class:`ProtocolError` (HTTP 400); VALUE errors (bad
    temperature, unknown slo class, quota-breaking tenant string) are
    left to ``build_request`` — one validation source, the same errors
    the library path raises."""
    if not isinstance(body, dict):
        raise ProtocolError(400, "request body must be a JSON object")
    unknown = sorted(set(body) - _KNOWN_FIELDS)
    if unknown:
        raise ProtocolError(
            400, f"unknown request field(s) {unknown}; supported: "
            f"{sorted(_KNOWN_FIELDS)}")
    if body.get("n", 1) not in (1, None):
        raise ProtocolError(400, "n > 1 is not supported")
    if body.get("stop") not in (None, [], ()):
        raise ProtocolError(
            400, "stop sequences are not supported — pass "
            "eos_token_id (token-level engine)")
    prompt = body.get("prompt")
    if isinstance(prompt, (int, np.integer)) \
            and not isinstance(prompt, bool):
        prompt = [prompt]
    if not isinstance(prompt, (list, tuple)) or not prompt or not all(
            isinstance(t, (int, np.integer))
            and not isinstance(t, bool) for t in prompt):
        raise ProtocolError(
            400, "prompt must be a non-empty array of token ids "
            "(this deployment serves token-level requests; there is "
            "no tokenizer)")
    max_tokens = body.get("max_tokens", 16)
    if isinstance(max_tokens, bool) or not isinstance(max_tokens, int) \
            or max_tokens < 1:
        raise ProtocolError(
            400, f"max_tokens must be a positive int; got "
            f"{max_tokens!r}")
    for key in ("stream", "echo", "greedy"):
        if key in body and body[key] is not None \
                and not isinstance(body[key], bool):
            raise ProtocolError(400, f"{key} must be a boolean")
    for key in ("tenant", "slo", "model"):
        if key in body and body[key] is not None \
                and not isinstance(body[key], str):
            raise ProtocolError(400, f"{key} must be a string")
    eos = body.get("eos_token_id")
    if eos is not None and (isinstance(eos, bool)
                            or not isinstance(eos, int)):
        raise ProtocolError(400, "eos_token_id must be an int")
    top_k = body.get("top_k")
    if top_k is not None and (isinstance(top_k, bool)
                              or not isinstance(top_k, int)):
        raise ProtocolError(400, "top_k must be an int")
    return CompletionRequest(
        prompt=np.asarray(prompt, np.int64),
        max_tokens=max_tokens,
        stream=bool(body.get("stream", False)),
        echo=bool(body.get("echo", False)),
        model=body.get("model") or "",
        tenant=body.get("tenant"),
        slo=body.get("slo"),
        ttft_target_ms=_opt_num(body, "ttft_target_ms"),
        tpot_target_ms=_opt_num(body, "tpot_target_ms"),
        deadline_ms=_opt_num(body, "deadline_ms"),
        temperature=_opt_num(body, "temperature"),
        top_k=top_k,
        top_p=_opt_num(body, "top_p"),
        greedy=body.get("greedy"),
        eos_token_id=eos,
    )


def render_text(tokens: List[int]) -> str:
    """The tokenizer-less ``text`` rendering: space-joined token ids
    (documented in README; ``token_ids`` carries the real payload)."""
    return " ".join(str(int(t)) for t in tokens)


def completion_chunk(cid: str, model: str, tokens: List[int],
                     finish_reason: Optional[str] = None) -> dict:
    """One SSE streaming chunk: the DELTA tokens accepted since the
    previous chunk (spec-decode's multi-token commits arrive as
    multi-token deltas — the user-visible latency win)."""
    return {
        "id": cid,
        "object": "text_completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "text": render_text(tokens),
            "token_ids": [int(t) for t in tokens],
            "finish_reason": finish_reason,
        }],
    }


def completion_response(cid: str, model: str, tokens: List[int],
                        finish_reason: Optional[str],
                        prompt_tokens: int,
                        echo_tokens: Optional[List[int]] = None) -> dict:
    """The non-streaming (aggregate) completion body."""
    ids = ([int(t) for t in echo_tokens] if echo_tokens else []) \
        + [int(t) for t in tokens]
    return {
        "id": cid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "text": render_text(ids),
            "token_ids": ids,
            "finish_reason": finish_reason,
        }],
        "usage": {
            "prompt_tokens": int(prompt_tokens),
            "completion_tokens": len(tokens),
            "total_tokens": int(prompt_tokens) + len(tokens),
        },
    }


def error_body(message: str, etype: str = "invalid_request_error") -> bytes:
    return json.dumps(
        {"error": {"message": str(message), "type": etype}}).encode()


def models_payload(model_id: str) -> dict:
    return {
        "object": "list",
        "data": [{
            "id": model_id,
            "object": "model",
            "owned_by": "paddle_tpu",
        }],
    }


def sse_data(obj: dict) -> bytes:
    """One server-sent-event frame."""
    return b"data: " + json.dumps(obj).encode() + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"
