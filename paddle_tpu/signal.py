"""paddle_tpu.signal — STFT / ISTFT (parity: python/paddle/signal.py,
backed upstream by the frame/overlap_add phi kernels).

TPU design: framing is a gather with a static [num_frames, n_fft] index
grid and overlap-add is a scatter-add (``.at[].add``) — both XLA-native,
jit/grad-friendly, no Python loops. FFTs go through jnp.fft (XLA Fft HLO).
"""

from __future__ import annotations

import jax.numpy as jnp


def frame(x, frame_length, hop_length, axis=-1):
    """Parity: paddle.signal.frame — slide a window of ``frame_length``
    every ``hop_length`` samples. Returns [..., frame_length, num_frames]
    for axis=-1 (paddle layout)."""
    if axis not in (-1, 0):
        raise ValueError("frame: axis must be 0 or -1")
    if axis == 0:
        # [seq, ...] → operate on the front axis
        moved = jnp.moveaxis(x, 0, -1)
        out = frame(moved, frame_length, hop_length, axis=-1)
        # [..., frame_length, num_frames] → [num_frames, frame_length, ...]
        return jnp.moveaxis(jnp.moveaxis(out, -1, 0), -1, 1)
    n = x.shape[-1]
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(num_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    frames = x[..., idx]                     # [..., num_frames, frame_length]
    return jnp.swapaxes(frames, -1, -2)      # [..., frame_length, num_frames]


def overlap_add(x, hop_length, axis=-1):
    """Parity: paddle.signal.overlap_add — inverse of ``frame``.
    x: [..., frame_length, num_frames] for axis=-1."""
    if axis not in (-1, 0):
        raise ValueError("overlap_add: axis must be 0 or -1")
    if axis == 0:
        moved = jnp.moveaxis(jnp.moveaxis(x, 1, -1), 0, -1)
        return jnp.moveaxis(
            overlap_add(moved, hop_length, axis=-1), -1, 0
        )
    frame_length, num_frames = x.shape[-2], x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    idx = (jnp.arange(num_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])          # [nf, fl]
    frames = jnp.swapaxes(x, -1, -2)                     # [..., nf, fl]
    batch_shape = frames.shape[:-2]
    flat = frames.reshape((-1,) + frames.shape[-2:])
    out = jnp.zeros((flat.shape[0], out_len), flat.dtype)
    out = out.at[:, idx].add(flat)
    return out.reshape(batch_shape + (out_len,))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Parity: paddle.signal.stft. x: real or complex [..., seq_len].
    Returns complex [..., n_fft//2+1 (onesided) or n_fft, num_frames]."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    is_complex = jnp.iscomplexobj(x)
    if is_complex and onesided:
        raise ValueError(
            "stft: onesided=True is only valid for real input (parity: "
            "paddle.signal.stft asserts the same)")
    if window is None:
        window = jnp.ones((win_length,), jnp.float32)
    window = jnp.asarray(window)
    if win_length < n_fft:  # center-pad the window to n_fft (paddle/torch)
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = frame(x, n_fft, hop_length, axis=-1)  # [..., n_fft, nf]
    frames = jnp.swapaxes(frames, -1, -2) * window  # [..., nf, n_fft]
    if onesided:
        spec = jnp.fft.rfft(frames, axis=-1)
    else:
        spec = jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return jnp.swapaxes(spec, -1, -2)  # [..., freq, num_frames]


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Parity: paddle.signal.istft — least-squares inverse via windowed
    overlap-add normalized by the window-square envelope."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), jnp.float32)
    window = jnp.asarray(window)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))

    spec = jnp.swapaxes(x, -1, -2)  # [..., num_frames, freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, axis=-1)
        if not return_complex:
            frames = frames.real
    frames = frames * window
    y = overlap_add(jnp.swapaxes(frames, -1, -2), hop_length, axis=-1)

    # window-square envelope for the least-squares normalization
    num_frames = x.shape[-1]
    wsq = jnp.square(window)
    env = overlap_add(
        jnp.broadcast_to(wsq[:, None], (n_fft, num_frames)),
        hop_length, axis=-1,
    )
    y = y / jnp.where(env > 1e-11, env, 1.0)

    if center:
        y = y[..., n_fft // 2:]
    if length is not None:
        y = y[..., :length]
    else:
        # drop the trailing center pad (paddle default: full OLA minus pad)
        if center:
            y = y[..., : y.shape[-1] - n_fft // 2]
    return y
