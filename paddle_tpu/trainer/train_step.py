"""Sharded train-step builder — the Fleet engine's hot loop.

Parity: the composite of fleet.distributed_model + HybridParallelOptimizer
+ the 1-step path of PipelineParallel/GroupSharded wrappers (SURVEY.md
§3.3). One call builds a single jitted XLA program that contains forward,
backward, gradient reduction, clipping, and the sharded optimizer update —
the work the reference splits across Reducer hooks, sharding-stage
wrappers and fused-kernel optimizers, all scheduled by XLA with
comm/compute overlap.

Donation: params and optimizer state are donated, so the update is
in-place in HBM (parity: in-place fused adamw).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import flags, observability
from ..core.functional import extract_param_objs, functional_call
from ..core.module import Layer
from ..distributed.sharding import (
    batch_spec,
    mesh_context,
    opt_slot_partition_spec,
    param_partition_spec,
)
from ..distributed.strategy import DistributedStrategy
from ..optimizer.optimizer import Optimizer


def _batch_tokens(batch) -> int:
    """Telemetry unit count: tokens for LM batches (first integer 2-D
    leaf), else the leading batch dim (samples)."""
    sample = 0
    for v in batch.values():
        if not hasattr(v, "ndim") or v.ndim == 0:
            continue
        if not sample:
            sample = int(v.shape[0])
        dt = getattr(v, "dtype", None)
        if v.ndim == 2 and dt is not None and \
                jnp.issubdtype(dt, jnp.integer):
            return int(v.shape[0]) * int(v.shape[1])
    return sample


def _param_shardings(param_objs, mesh, strategy):
    return {
        name: NamedSharding(
            mesh, param_partition_spec(name, p.shape, p.spec, strategy)
        )
        for name, p in param_objs.items()
    }


def _state_shardings(state_shape, param_objs, mesh, strategy):
    """Mirror the optimizer state structure with shardings: any leaf whose
    shape equals its parameter's shape gets the opt-slot spec; scalars and
    odd-shaped leaves are replicated."""
    repl = NamedSharding(mesh, P())

    def slot_sharding(name, leaf):
        p = param_objs[name]
        if tuple(leaf.shape) == tuple(p.shape):
            return NamedSharding(
                mesh, opt_slot_partition_spec(name, p.shape, p.spec, strategy)
            )
        return repl

    out = {"step": repl, "slots": {}}
    for name, slots in state_shape["slots"].items():
        out["slots"][name] = {
            k: slot_sharding(name, v) for k, v in slots.items()
        }
    if "master" in state_shape:
        out["master"] = {
            name: slot_sharding(name, leaf)
            for name, leaf in state_shape["master"].items()
        }
    return out


class TrainStep:
    """Compiled train step + its sharded state.

    Usage:
        ts = TrainStep(model, optimizer, mesh, strategy, loss_fn)
        metrics = ts.run(batch)          # one optimizer step
        ts.sync_to_model()               # write params back into Layers
    """

    def __init__(
        self,
        model: Layer,
        optimizer: Optimizer,
        mesh: Mesh,
        strategy: Optional[DistributedStrategy] = None,
        loss_fn: Optional[Callable] = None,
        batch_seq_axis: Optional[int] = 1,
        donate: bool = True,
        rng_seed: int = 0,
        abstract: bool = False,
        master_residency: str = "paired",
        telemetry=None,
    ):
        """``abstract=True`` builds the full sharded step WITHOUT
        materializing parameters or optimizer state — params may be
        ``jax.ShapeDtypeStruct`` (core.meta.meta_init). Use ``lower()``
        for AOT compilation / per-device memory planning of configs far
        larger than host memory (the 70B north-star path); ``run()`` is
        unavailable.

        ``master_residency``: ``"paired"`` (default) keeps params at
        model dtype alongside fp32 masters in optimizer state — the
        classic layout. ``"master_only"`` drops the persistent
        low-precision copies: the fp32 master is the ONLY resident form
        of each bf16/fp16 parameter, and the compute-dtype view is cast
        transiently inside the step. Numerics are bit-identical to
        "paired" (the stored bf16 param is exactly cast(master) after
        every update), but steady HBM residency shrinks by
        itemsize(model_dtype) bytes/param — ~1.75 GB on the 876M
        headline — which is what buys the larger batch (parity intent:
        fleet GroupShardedOptimizerStage2 master-weight handling, which
        likewise keeps one authoritative fp32 copy).

        ``telemetry``: ``None`` (default) auto-wires an
        ``observability.TrainTelemetry`` when ``PT_FLAGS_telemetry`` is
        on; ``False`` disables instrumentation for this step; or pass a
        preconfigured ``TrainTelemetry`` (custom sampling cadence /
        flight-recorder window). When enabled, the compiled step also
        emits the global gradient norm — sampled steps publish loss /
        grad-norm / tokens-per-sec / MFU / memory through the registry
        and feed the flight recorder + NaN watchdog; non-sampled steps
        never force an extra host sync."""
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.strategy = strategy or DistributedStrategy()
        self.loss_fn = loss_fn
        self.batch_seq_axis = batch_seq_axis
        self.abstract = abstract

        self.master_residency = master_residency
        self._master_dtypes: Dict[str, jnp.dtype] = {}

        self._param_objs = extract_param_objs(model, trainable_only=True)
        self.param_shardings = _param_shardings(
            self._param_objs, mesh, self.strategy
        )
        if abstract:
            self.params = {
                n: (p.value if isinstance(p.value, jax.ShapeDtypeStruct)
                    else jax.ShapeDtypeStruct(
                        tuple(p.value.shape), p.value.dtype))
                for n, p in self._param_objs.items()
            }
        else:
            # place params
            self.params = {
                n: jax.device_put(p.value, self.param_shardings[n])
                for n, p in self._param_objs.items()
            }
        # sharded optimizer state, created on-device under jit
        state_shape = jax.eval_shape(optimizer.init, self.params)
        self.state_shardings = _state_shardings(
            state_shape, self._param_objs, mesh, self.strategy
        )
        if abstract:
            self.opt_state = state_shape
        else:
            with mesh_context(mesh):
                self.opt_state = jax.jit(
                    optimizer.init, out_shardings=self.state_shardings
                )(self.params)

            # keep the Layer tree pointing at the live arrays: device_put
            # may alias the original buffers, and step donation would
            # otherwise leave Parameters referencing deleted arrays
            self.sync_to_model()

        # master-only residency: the fp32 master in optimizer state is
        # the single persistent copy; drop the model-dtype duplicates
        # from the step's carried params
        if master_residency not in ("paired", "master_only"):
            raise ValueError(
                f"master_residency must be 'paired' or 'master_only', "
                f"got {master_residency!r}")
        master_names = set(state_shape.get("master", {}))
        if master_residency == "master_only" and not master_names:
            raise ValueError(
                "master_residency='master_only' needs fp32 masters: use "
                "an optimizer with multi_precision=True and bf16/fp16 "
                "parameters")
        if master_residency == "master_only":
            self._master_dtypes = {
                n: self.params[n].dtype for n in master_names
            }
            for n in master_names:
                # release the Layer tree's reference too, or the bf16
                # device buffer stays alive and nothing is saved; the
                # Parameter holds a meta struct until sync_to_model()
                v = self.params[n]
                if not isinstance(v, jax.ShapeDtypeStruct):
                    self._param_objs[n].value = jax.ShapeDtypeStruct(
                        tuple(v.shape), v.dtype)
            self.params = {n: v for n, v in self.params.items()
                           if n not in master_names}
        carried_param_shardings = {
            n: s for n, s in self.param_shardings.items()
            if n in self.params
        }
        master_dtypes = self._master_dtypes

        self.step_count = 0
        self._rng_key = jax.random.PRNGKey(rng_seed)

        # telemetry: the grad-norm output is baked into the compiled
        # step only when instrumentation is live, so telemetry-off
        # compiles the exact pre-telemetry program (zero overhead).
        # abstract mode keeps the same program shape (AOT memory plans
        # must match what a real run would compile) but holds no
        # telemetry object.
        want_tel = (observability.enabled() if telemetry is None
                    else bool(telemetry))
        # check_nan_inf promises a grad-norm check: it needs the gnorm
        # output even when telemetry is off (flag read at BUILD time —
        # the program's output arity is a compile-time shape)
        emit_gnorm = want_tel or bool(flags.flag("check_nan_inf"))
        self._emit_gnorm = emit_gnorm
        self.telemetry = None
        if want_tel and not abstract:
            self.telemetry = (
                telemetry
                if isinstance(telemetry, observability.TrainTelemetry)
                else observability.TrainTelemetry())
        self._flops_per_step = None
        self._flops_probed = False

        model_ref = model
        loss_ref = loss_fn
        merge_k = (self.strategy.gradient_merge_k_steps
                   if getattr(self.strategy, "gradient_merge", False) else 1)
        self.gradient_merge_k = merge_k

        def loss_of(p, batch, rng):
            rngs = {"dropout": rng, "default": rng}
            if loss_ref is None:
                # model computes its own scalar loss from the batch dict
                return functional_call(model_ref, p, **batch, rngs=rngs)
            out = functional_call(model_ref, p, batch["input"], rngs=rngs)
            return loss_ref(out, batch["label"])

        def step_fn(params, opt_state, batch, rng):
            if master_dtypes:
                # rebuild the compute-dtype view from the resident fp32
                # masters; XLA sees cast(master) feeding the matmuls and
                # may rematerialize the casts under memory pressure
                # instead of keeping 2 bytes/param alive across the step
                params = dict(params)
                for n, dt in master_dtypes.items():
                    params[n] = opt_state["master"][n].astype(dt)
            if merge_k <= 1:
                loss, grads = jax.value_and_grad(loss_of)(
                    params, batch, rng)
            else:
                # gradient merge (parity: fleet gradient_merge /
                # accumulate_steps): split the global batch into k
                # micro-batches and scan — one live micro-batch of
                # activations at a time, fp32 grad accumulators, a single
                # optimizer update. One compiled program, no host loop.
                def is_batched(v):
                    return hasattr(v, "ndim") and v.ndim > 0

                static_part = {k: v for k, v in batch.items()
                               if not is_batched(v)}

                def reshape_mb(v):
                    b = v.shape[0]
                    if b % merge_k:
                        raise ValueError(
                            f"gradient_merge: batch {b} not divisible by "
                            f"k_steps {merge_k}")
                    return v.reshape(merge_k, b // merge_k, *v.shape[1:])

                mbatch = {k: reshape_mb(v) for k, v in batch.items()
                          if is_batched(v)}
                rngs_k = jax.random.split(rng, merge_k)
                zero = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)

                def body(carry, xs):
                    acc, loss_sum = carry
                    mb, r = xs
                    mb = {**mb, **static_part}
                    mb = jax.tree_util.tree_map(
                        lambda v: jax.lax.with_sharding_constraint(
                            v, NamedSharding(mesh, batch_spec(
                                v.ndim, self.batch_seq_axis
                                if v.ndim > 1 else None, self.strategy)))
                        if hasattr(v, "ndim") and v.ndim > 0 else v, mb)
                    loss, grads = jax.value_and_grad(loss_of)(params, mb, r)
                    acc = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(jnp.float32), acc, grads)
                    return (acc, loss_sum + loss), None

                (acc, loss_sum), _ = jax.lax.scan(
                    body, (zero, jnp.zeros((), jnp.float32)),
                    (mbatch, rngs_k))
                grads = jax.tree_util.tree_map(
                    lambda a: a / merge_k, acc)
                loss = loss_sum / merge_k
            if emit_gnorm:
                # pre-clip global grad norm, fp32 accumulation — a
                # single reduction pass, negligible next to fwd+bwd
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads)))
            new_params, new_state = optimizer.update(grads, opt_state, params)
            if master_dtypes:
                # the low-precision copies are not carried: drop them so
                # XLA dead-code-eliminates the cast-back
                new_params = {n: v for n, v in new_params.items()
                              if n not in master_dtypes}
            if emit_gnorm:
                return new_params, new_state, loss, gnorm
            return new_params, new_state, loss

        donate_argnums = (0, 1) if donate else ()
        repl = NamedSharding(mesh, P())
        out_shardings = (carried_param_shardings, self.state_shardings,
                         repl)
        if emit_gnorm:
            out_shardings = out_shardings + (repl,)
        self._step = jax.jit(
            step_fn,
            in_shardings=(
                carried_param_shardings,
                self.state_shardings,
                None,  # batch shardings resolve from committed inputs
                NamedSharding(mesh, P()),
            ),
            out_shardings=out_shardings,
            donate_argnums=donate_argnums,
        )

    # ------------------------------------------------------------------
    def shard_batch(self, batch: Dict[str, jax.Array]):
        out = {}
        for k, v in batch.items():
            seq_ax = self.batch_seq_axis if (
                hasattr(v, "ndim") and v.ndim > 1
            ) else None
            sh = NamedSharding(
                self.mesh, batch_spec(getattr(v, "ndim", 1), seq_ax,
                                      self.strategy)
            )
            out[k] = jax.device_put(v, sh)
        return out

    def lower(self, batch_shapes: Dict):
        """AOT-lower the full sharded train step against abstract inputs.

        ``batch_shapes``: dict of arrays or ShapeDtypeStructs. Returns a
        ``jax.stages.Lowered``; ``.compile().memory_analysis()`` gives
        the per-device argument/temp byte plan (parity: the memory
        estimation pass of the reference's static auto-parallel engine,
        distributed/auto_parallel/static/engine.py)."""
        batch = {
            k: jax.ShapeDtypeStruct(
                tuple(v.shape), v.dtype,
                sharding=NamedSharding(
                    self.mesh,
                    batch_spec(
                        len(v.shape),
                        self.batch_seq_axis if len(v.shape) > 1 else None,
                        self.strategy,
                    ),
                ),
            )
            for k, v in batch_shapes.items()
        }
        rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
        with mesh_context(self.mesh):
            return self._step.lower(self.params, self.opt_state, batch, rng)

    def run(self, batch: Dict, sharded: bool = False):
        if self.abstract:
            raise RuntimeError(
                "TrainStep(abstract=True) holds no real parameters; "
                "use lower() for AOT compilation, or rebuild without "
                "abstract for execution")
        tel = self.telemetry
        bench = bool(flags.flag("benchmark"))
        t0 = time.perf_counter() if tel is not None or bench else 0.0
        if not sharded:
            batch = self.shard_batch(batch)
        self._rng_key, sub = jax.random.split(self._rng_key)
        gnorm = None
        with mesh_context(self.mesh):
            if self._emit_gnorm:
                self.params, self.opt_state, loss, gnorm = self._step(
                    self.params, self.opt_state, batch, sub
                )
            else:
                self.params, self.opt_state, loss = self._step(
                    self.params, self.opt_state, batch, sub
                )
        self.step_count += 1
        if bench or flags.flag("check_nan_inf"):
            # debug knobs — BOTH force a host sync on the step's
            # outputs, which is their documented cost (the telemetry
            # path below never syncs off-sample; these flags exist for
            # the runs where per-step truth beats throughput)
            loss_f = float(jnp.asarray(loss))
            gnorm_f = (float(jnp.asarray(gnorm))
                       if gnorm is not None else None)
            if bench:
                wall_ms = (time.perf_counter() - t0) * 1e3
                print(f"[pt-benchmark] step {self.step_count}: "
                      f"{wall_ms:.2f} ms  loss={loss_f:.6g}"
                      + (f"  grad_norm={gnorm_f:.6g}"
                         if gnorm_f is not None else ""),
                      flush=True)
            if flags.flag("check_nan_inf"):
                import math as _math

                if gnorm_f is None and not self._emit_gnorm:
                    # flag flipped on AFTER build: output arity is a
                    # compile-time shape, so only loss is checkable —
                    # say so once instead of silently half-checking
                    if not getattr(self, "_warned_nan_loss_only", False):
                        self._warned_nan_loss_only = True
                        import warnings

                        warnings.warn(
                            "PT_FLAGS_check_nan_inf was enabled after "
                            "this TrainStep was built: grad-norm is "
                            "not emitted, so only the loss is checked "
                            "— rebuild the TrainStep to check "
                            "gradients too", stacklevel=2)
                bad = [n for n, v in (("loss", loss_f),
                                      ("grad_norm", gnorm_f))
                       if v is not None and not _math.isfinite(v)]
                if bad:
                    raise FloatingPointError(
                        f"PT_FLAGS_check_nan_inf: non-finite "
                        f"{'/'.join(bad)} at step {self.step_count} "
                        f"(loss={loss_f}, grad_norm={gnorm_f})")
        if tel is not None:
            # loss/gnorm stay async device futures unless this is a
            # sampled step (TrainTelemetry fetches them only then)
            tel.on_step(
                self.step_count, loss, gnorm,
                tokens=_batch_tokens(batch),
                wall_s=time.perf_counter() - t0,
                flops_getter=lambda: self._cost_flops(batch, sub))
        if not self._master_dtypes:
            self.sync_to_model()
        else:
            # master_only: skip the write-back ONLY for master-backed
            # params (re-materializing them defeats the mode; call
            # sync_to_model() explicitly before eval/export). Carried
            # params (fp32, no master) were donated and MUST be rebound
            # or their Parameters point at deleted buffers.
            for n in self.params:
                self._param_objs[n].value = self.params[n]
        if self.optimizer._lr_scheduler is not None:
            self.optimizer._lr_scheduler.step()
        return loss

    def _cost_flops(self, batch, rng):
        """Per-step FLOPs from XLA cost analysis, probed once (the
        lowering retrace + compile-cache hit costs one sampled step,
        never the steady loop); None when the backend can't say."""
        if self._flops_probed:
            return self._flops_per_step
        self._flops_probed = True
        try:
            with mesh_context(self.mesh):
                ca = self._step.lower(
                    self.params, self.opt_state, batch, rng
                ).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            f = (ca or {}).get("flops")
            self._flops_per_step = float(f) if f and f > 0 else None
        except Exception:
            self._flops_per_step = None
        return self._flops_per_step

    def _materialized_params(self):
        """Full param dict at model dtype; in master_only mode the
        dropped copies are cast back from the fp32 masters on demand."""
        params = dict(self.params)
        for n, dt in self._master_dtypes.items():
            params[n] = self.opt_state["master"][n].astype(dt)
        return params

    def sync_to_model(self):
        """Write the (sharded) param values back into the Layer tree."""
        for n, p in self._param_objs.items():
            if n in self._master_dtypes:
                p.value = self.opt_state["master"][n].astype(
                    self._master_dtypes[n])
            elif n in self.params:
                p.value = self.params[n]

    def state_dict(self):
        return {
            "params": self._materialized_params(),
            "opt_state": self.opt_state,
            "step": self.step_count,
        }

    def set_state_dict(self, sd):
        # merge, don't replace: a partial restore must not wipe params
        # absent from sd (the carried-params pytree has to keep matching
        # the compiled step's structure)
        new_params = dict(self.params)
        for n, v in sd["params"].items():
            if n not in self._master_dtypes:
                new_params[n] = jax.device_put(v, self.param_shardings[n])
        self.params = new_params
        if "opt_state" not in sd and self.opt_state.get("master"):
            # params-only restore with live fp32 masters (either mode):
            # the masters are what the next update reads — refresh them
            # or the restore is silently overwritten on the first step
            new_master = dict(self.opt_state["master"])
            for n in new_master:
                if n in sd["params"]:
                    new_master[n] = jax.device_put(
                        jnp.asarray(sd["params"][n]).astype(jnp.float32),
                        self.state_shardings["master"][n])
            self.opt_state = {**self.opt_state, "master": new_master}
        if "opt_state" in sd:
            self.opt_state = jax.device_put(
                sd["opt_state"], self.state_shardings
            )
        # a checkpoint round-trip returns 'step' as a 0-d array; keep the
        # counter a python int (log lines, ckpt filenames format it)
        self.step_count = int(sd.get("step", 0))


def build_train_step(model, optimizer, mesh, strategy=None, loss_fn=None,
                     **kw) -> TrainStep:
    return TrainStep(model, optimizer, mesh, strategy, loss_fn, **kw)
