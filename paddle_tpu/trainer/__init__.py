from .train_step import TrainStep, build_train_step  # noqa: F401
