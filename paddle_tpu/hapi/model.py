"""High-level Model API (parity: python/paddle/hapi/model.py —
``paddle.Model(net).prepare(optimizer, loss, metrics)`` then
``fit/evaluate/predict/save/load``)."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.functional import extract_params, functional_call
from ..core.module import Layer
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import Callback, CallbackList, ProgBarLogger


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._jit_train = None
        self._jit_eval = None
        self._opt_state = None

    def prepare(self, optimizer=None, loss=None, metrics=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = list(metrics or [])
        net = self.network
        loss_fn = loss

        def train_step(params, opt_state, x, y, rng):
            def loss_of(p):
                out = functional_call(net, p, x, rngs={"dropout": rng})
                return loss_fn(out, y), out

            (lv, out), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(params)
            params, opt_state = optimizer.update(grads, opt_state, params)
            return params, opt_state, lv, out

        def eval_step(params, x, y):
            out = functional_call(net, params, x)
            return loss_fn(out, y), out

        self._jit_train = jax.jit(train_step) if optimizer else None
        self._jit_eval = jax.jit(eval_step) if loss else None
        return self

    # ------------------------------------------------------------------
    def _loader(self, data, batch_size, shuffle):
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        raise TypeError(f"unsupported data type {type(data)}")

    def fit(
        self,
        train_data=None,
        eval_data=None,
        batch_size: int = 1,
        epochs: int = 1,
        verbose: int = 1,
        callbacks: Optional[List[Callback]] = None,
        shuffle: bool = True,
        log_freq: int = 10,
    ):
        loader = self._loader(train_data, batch_size, shuffle)
        cbs = CallbackList(callbacks)
        if verbose:
            cbs.append(ProgBarLogger(log_freq, verbose))
        cbs.set_model(self)
        params = extract_params(self.network)
        if self._opt_state is None:
            self._opt_state = self._optimizer.init(params)
        rng = jax.random.PRNGKey(0)
        cbs.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbs.on_epoch_begin(epoch)
            epoch_loss = 0.0
            nb = 0
            if hasattr(loader.batch_sampler, "set_epoch"):
                loader.batch_sampler.set_epoch(epoch)
            for step, batch in enumerate(loader):
                x, y = batch[0], batch[1]
                rng, sub = jax.random.split(rng)
                cbs.on_train_batch_begin(step)
                params, self._opt_state, lv, out = self._jit_train(
                    params, self._opt_state, jnp.asarray(x), jnp.asarray(y),
                    sub,
                )
                lv = float(lv)
                epoch_loss += lv
                nb += 1
                logs = {"loss": lv}
                for m in self._metrics:
                    m.update(np.asarray(out), np.asarray(y))
                    logs[m.name()] = m.accumulate()
                cbs.on_train_batch_end(step, logs)
            # write trained params back into the network
            objs = dict(self.network.named_parameters())
            for n, v in params.items():
                if n in objs:
                    objs[n].value = v
            logs = {"loss": epoch_loss / max(nb, 1)}
            if eval_data is not None:
                eval_logs = self.evaluate(
                    eval_data, batch_size=batch_size, verbose=0
                )
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
                cbs.on_eval_end(eval_logs)
            for m in self._metrics:
                m.reset()
            cbs.on_epoch_end(epoch, logs)
        cbs.on_train_end()
        return self

    def evaluate(self, eval_data, batch_size: int = 1, verbose: int = 1):
        loader = self._loader(eval_data, batch_size, shuffle=False)
        self.network.eval()
        params = extract_params(self.network)
        total, nb = 0.0, 0
        for m in self._metrics:
            m.reset()
        for batch in loader:
            x, y = batch[0], batch[1]
            lv, out = self._jit_eval(params, jnp.asarray(x), jnp.asarray(y))
            total += float(lv)
            nb += 1
            for m in self._metrics:
                m.update(np.asarray(out), np.asarray(y))
        self.network.train()
        logs = {"loss": total / max(nb, 1)}
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        if verbose:
            print("Eval:", logs)
        return logs

    def predict(self, test_data, batch_size: int = 1):
        loader = self._loader(test_data, batch_size, shuffle=False)
        self.network.eval()
        params = extract_params(self.network)
        fn = jax.jit(lambda p, x: functional_call(self.network, p, x))
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            outs.append(np.asarray(fn(params, jnp.asarray(x))))
        self.network.train()
        return np.concatenate(outs, axis=0)

    def save(self, path: str):
        from ..framework import io as fio

        fio.save(self.network.state_dict(), path + ".pdparams")
        if self._opt_state is not None:
            fio.save(self._opt_state, path + ".pdopt")

    def load(self, path: str):
        from ..framework import io as fio

        self.network.set_state_dict(fio.load(path + ".pdparams"))
        import os

        if os.path.exists(path + ".pdopt"):
            self._opt_state = fio.load(path + ".pdopt")
        return self

    def parameters(self):
        return self.network.parameters()

    def summary(self):
        n_params = sum(int(np.prod(p.shape)) for p in self.network.parameters())
        lines = [repr(self.network), f"Total params: {n_params:,}"]
        text = "\n".join(lines)
        print(text)
        return {"total_params": n_params}
