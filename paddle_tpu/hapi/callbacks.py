"""High-level training callbacks (parity: python/paddle/hapi/callbacks.py
— Callback ABC, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler, VisualDL→ a generic scalar logger, plus MetricsLogger
publishing through the observability registry).

Scalar emission (ProgBarLogger prints, VisualDL JSONL) is also routed
through ``observability.record_scalars`` so hapi training feeds the
process-wide telemetry registry for free."""

from __future__ import annotations

import os
import time
from typing import List, Optional

from ..observability import record_scalars


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)

            return dispatch
        raise AttributeError(name)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)


class ProgBarLogger(Callback):
    def __init__(self, log_freq: int = 10, verbose: int = 1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.perf_counter()
        if self.verbose:
            print(f"Epoch {epoch + 1}")

    def on_train_batch_end(self, step, logs=None):
        if step % self.log_freq == 0:
            record_scalars("hapi_train", logs)
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            print(f"  step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.perf_counter() - self.t0
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (logs or {}).items()
            )
            print(f"  epoch {epoch + 1} done in {dt:.1f}s - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq: int = 1, save_dir: str = "ckpt"):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"epoch_{epoch}")
            self.model.save(path)


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0,
                 min_delta=0.0, baseline=None):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = baseline
        self.wait = 0
        self.stopped_epoch = None

    def _better(self, cur, best):
        if best is None:
            return True
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True


class LRSchedulerCallback(Callback):
    """Steps the optimizer's LR scheduler per epoch (parity: hapi
    LRScheduler callback; per-step scheduling happens inside TrainStep)."""

    def __init__(self, by_step: bool = False):
        self.by_step = by_step

    def on_epoch_end(self, epoch, logs=None):
        sched = getattr(self.model._optimizer, "_lr_scheduler", None)
        if sched is not None and not self.by_step:
            sched.step()


# paddle name: callbacks.LRScheduler
LRScheduler = LRSchedulerCallback


class ReduceLROnPlateau(Callback):
    """Parity: hapi callbacks.ReduceLROnPlateau — shrink the scheduler
    LR when ``monitor`` stops improving."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 mode="min", min_delta=1e-4, cooldown=0, min_lr=0.0,
                 verbose=1):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.verbose = verbose
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(cur):
            self.best = cur
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = self.model._optimizer
            sched = getattr(opt, "_lr_scheduler", None)
            target = sched if sched is not None else opt
            old = float(getattr(target, "base_lr",
                                getattr(target, "learning_rate", 0.0)))
            new = max(old * self.factor, self.min_lr)
            if hasattr(target, "base_lr"):
                target.base_lr = new
            else:
                target.learning_rate = new
            if self.verbose:
                print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
            self.wait = 0
            self.cooldown_counter = self.cooldown


class VisualDL(Callback):
    """Parity: hapi callbacks.VisualDL. The visualdl package is not
    available in this environment; scalars are appended to a JSONL
    file a local VisualDL/TensorBoard shim can tail."""

    def __init__(self, log_dir="vdl_log"):
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json

        record_scalars(f"hapi_{tag}", logs)
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, "scalars.jsonl")
        rec = {"tag": tag, "step": self._step}
        for k, v in (logs or {}).items():
            try:
                rec[k] = float(v[0] if isinstance(v, (list, tuple)) else v)
            except (TypeError, ValueError):
                continue
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


class MetricsLogger(Callback):
    """Publish every hapi train/eval scalar through the observability
    registry (``pt_hapi_train_*`` / ``pt_hapi_eval_*`` gauges plus step
    and epoch counters), so ``Model.fit`` runs show up on the same
    ``/metrics`` scrape as TrainStep and the serving engine."""

    def __init__(self, log_freq: int = 1):
        self.log_freq = max(1, int(log_freq))

    def on_train_begin(self, logs=None):
        from ..observability import get_registry

        reg = get_registry()
        self._steps = reg.counter(
            "pt_hapi_steps_total", "hapi train batches")
        self._epochs = reg.counter(
            "pt_hapi_epochs_total", "hapi epochs completed")

    def on_train_batch_end(self, step, logs=None):
        self._steps.inc()
        if step % self.log_freq == 0:
            record_scalars("hapi_train", logs)

    def on_epoch_end(self, epoch, logs=None):
        self._epochs.inc()
        record_scalars("hapi_train", logs)

    def on_eval_end(self, logs=None):
        record_scalars("hapi_eval", logs)
