"""Model introspection (parity: paddle.summary / paddle.flops —
python/paddle/hapi/model_summary.py, hapi/dynamic_flops.py).

Implemented with forward post-hooks over one abstract-shape trace:
``jax.eval_shape`` runs the whole model without allocating or computing,
so summarizing a 70B-parameter model costs nothing — the TPU-world
version of the reference's hook-based dry run.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.module import Layer


def _norm_sizes(sz):
    """Accept one shape (tuple OR list of ints) or a list of shapes."""
    if sz is None:
        return None
    if isinstance(sz, (tuple, list)) and sz and all(
            isinstance(i, int) for i in sz):
        return [tuple(sz)]
    return [tuple(s) for s in sz]


def _shapes_of(out):
    if hasattr(out, "shape"):
        return [tuple(out.shape)]
    if isinstance(out, (tuple, list)):
        res = []
        for o in out:
            res.extend(_shapes_of(o))
        return res
    return []


def _collect(net: Layer, input_spec, dtypes, kwargs):
    """One eval_shape pass recording (layer, output shapes) per leaf."""
    records = []
    handles = []
    targets = list(net.named_sublayers(include_self=False))
    if not targets:              # the net itself is a single leaf layer
        targets = [("", net)]
    for name, sub in targets:
        if sub._sub_layers:      # only leaves get rows (reference style)
            continue

        def mk(name, sub):
            def hook(lyr, inputs, out):
                records.append({
                    "name": name,
                    "type": type(sub).__name__,
                    "out": _shapes_of(out),
                    "params": int(sum(
                        np.prod(p.shape)
                        for p in sub._parameters.values()
                        if p is not None)),
                    # MAC-bearing params only (weight matrices/filters,
                    # ndim>=2): paddle.flops counts 2*tokens*in*out and
                    # excludes bias vectors from the multiply count
                    "mac_params": int(sum(
                        np.prod(p.shape)
                        for p in sub._parameters.values()
                        if p is not None and len(p.shape) >= 2)),
                    "data_format": getattr(sub, "data_format", "NCHW"),
                    "in": _shapes_of(inputs),
                })
                return out

            return hook

        handles.append(sub.register_forward_post_hook(mk(name, sub)))

    try:
        args = [jax.ShapeDtypeStruct(s, d)
                for s, d in zip(input_spec, dtypes)]
        jax.eval_shape(lambda *a: net(*a, **kwargs), *args)
    finally:
        for h in handles:
            h.remove()
    return records


def summary(net: Layer, input_size=None, dtypes=None, input=None, **kwargs):  # noqa: A002
    """Parity: paddle.summary — prints the layer table, returns
    {'total_params', 'trainable_params'}."""
    if input is not None:
        specs = [tuple(np.asarray(x).shape) for x in (
            input if isinstance(input, (tuple, list)) else [input])]
        dts = [jnp.asarray(np.asarray(x)).dtype for x in (
            input if isinstance(input, (tuple, list)) else [input])]
    else:
        specs = _norm_sizes(input_size)
        dts = dtypes or [jnp.float32] * len(specs)
        if not isinstance(dts, (list, tuple)):
            dts = [dts] * len(specs)
    records = _collect(net, specs, dts, kwargs)

    header = f"{'Layer (type)':<38}{'Output Shape':<26}{'Param #':>12}"
    sep = "=" * len(header)
    lines = [sep, header, sep]
    for r in records:
        shape = str(r["out"][0] if len(r["out"]) == 1 else r["out"])
        lines.append(
            f"{r['name'] + ' (' + r['type'] + ')':<38}"
            f"{shape:<26}{r['params']:>12,}")
    all_params = int(sum(np.prod(p.shape)
                         for _, p in net.named_parameters()))
    trainable = int(sum(np.prod(p.shape)
                        for _, p in net.named_parameters() if p.trainable))
    lines += [sep,
              f"Total params: {all_params:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {all_params - trainable:,}",
              sep]
    print("\n".join(lines))
    return {"total_params": all_params, "trainable_params": trainable}


_FLOP_RULES = {}


def _rule(*type_names):
    def deco(fn):
        for t in type_names:
            _FLOP_RULES[t] = fn
        return fn

    return deco


@_rule("Linear", "ColumnParallelLinear", "RowParallelLinear")
def _linear_flops(rec):
    out = rec["out"][0]
    # 2 * tokens * in * out: weight MACs only, bias add excluded
    # (paddle.flops accounting)
    tokens = int(np.prod(out[:-1])) if len(out) > 1 else 1
    return 2 * tokens * rec["mac_params"]


@_rule("Conv2D", "Conv1D", "Conv3D", "Conv2DTranspose")
def _conv_flops(rec):
    out = rec["out"][0]
    # batch * spatial positions, layout-aware (channels sit at index 1
    # for NCHW-family formats, last otherwise)
    ch_axis = 1 if rec.get("data_format", "NCHW").startswith("NC") else -1
    spatial = int(np.prod(out)) // out[ch_axis]
    return 2 * spatial * rec["mac_params"]


@_rule("Embedding", "VocabParallelEmbedding")
def _emb_flops(rec):
    return 0


def flops(net: Layer, input_size, dtypes=None, print_detail=False,
          **kwargs):
    """Parity: paddle.flops — MAC-based FLOPs estimate from one abstract
    trace (matmul-bearing leaves; normalizations/activations are counted
    as 0, matching the reference's dominant-term accounting)."""
    input_size = _norm_sizes(input_size)
    dts = dtypes or [jnp.float32] * len(input_size)
    if not isinstance(dts, (list, tuple)):
        dts = [dts] * len(input_size)
    records = _collect(net, input_size, dts, kwargs)
    total = 0
    for r in records:
        rule = _FLOP_RULES.get(r["type"])
        if rule is not None and r["out"]:
            f = int(rule(r))
            total += f
            if print_detail:
                print(f"{r['name']:<40}{r['type']:<20}{f:>16,}")
    if print_detail:
        print(f"{'Total FLOPs:':<60}{total:>16,}")
    return total
