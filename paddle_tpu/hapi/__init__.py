from .callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRSchedulerCallback,
    ModelCheckpoint,
    ProgBarLogger,
)
from .model import Model  # noqa: F401
