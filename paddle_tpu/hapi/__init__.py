from .callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    LRSchedulerCallback,
    ReduceLROnPlateau,
    VisualDL,
    ModelCheckpoint,
    ProgBarLogger,
)
from .model import Model  # noqa: F401

from .summary import flops, summary  # noqa: F401,E402
