"""paddle.linalg parity surface over jnp.linalg."""

from __future__ import annotations

import jax.numpy as jnp

from .core.parameter import Parameter


def _v(x):
    return x.value if isinstance(x, Parameter) else x


def matmul(x, y, transpose_x=False, transpose_y=False):
    x, y = _v(x), _v(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def norm(x, p="fro", axis=None, keepdim=False):
    """Paddle semantics (axis=None flattens any rank; int axis → vector
    p-norm; tuple axis → matrix norm). Shares the tensor.norm impl."""
    from . import tensor as _tensor

    return _tensor.norm(_v(x), p=p, axis=axis, keepdim=keepdim)


def inv(x):
    return jnp.linalg.inv(_v(x))


def pinv(x, rcond=1e-15):
    return jnp.linalg.pinv(_v(x), rcond)


def det(x):
    return jnp.linalg.det(_v(x))


def slogdet(x):
    return jnp.linalg.slogdet(_v(x))


def svd(x, full_matrices=False):
    return jnp.linalg.svd(_v(x), full_matrices=full_matrices)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(_v(x), mode=mode)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(_v(x), UPLO=UPLO)


def eig(x):
    return jnp.linalg.eig(_v(x))


def cholesky(x, upper=False):
    out = jnp.linalg.cholesky(_v(x))
    return jnp.swapaxes(out, -1, -2) if upper else out


def solve(a, b):
    return jnp.linalg.solve(_v(a), _v(b))


def lstsq(a, b, rcond=None):
    return jnp.linalg.lstsq(_v(a), _v(b), rcond=rcond)


def matrix_rank(x, tol=None):
    return jnp.linalg.matrix_rank(_v(x), tol)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(_v(x), n)


def cond(x, p=None):
    return jnp.linalg.cond(_v(x), p)


def triangular_solve(a, b, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl

    return jsl.solve_triangular(
        _v(a), _v(b), lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular,
    )


def cholesky_solve(b, y, upper=False):
    """Parity: paddle.linalg.cholesky_solve — solve A x = b given the
    Cholesky factor y of A."""
    import jax.scipy.linalg as jsl

    return jsl.cho_solve((_v(y), not upper), _v(b))


def eigvals(x):
    return jnp.linalg.eigvals(_v(x))


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(_v(x), UPLO=UPLO)


def lu(x, pivot=True):
    """Parity: paddle.linalg.lu — packed LU plus pivots (1-based, paddle
    convention matching the LAPACK getrf output)."""
    import jax.scipy.linalg as jsl

    lu_mat, piv = jsl.lu_factor(_v(x))
    return lu_mat, piv + 1


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True):
    """Parity: paddle.linalg.lu_unpack → (P, L, U). 2-D only (batched
    unpack: vmap this)."""
    lu_mat = _v(lu_data)
    if lu_mat.ndim != 2:
        raise ValueError("lu_unpack: 2-D input only; vmap for batches")
    n = lu_mat.shape[-2]
    m = lu_mat.shape[-1]
    k = min(n, m)
    L = jnp.tril(lu_mat[..., :k], -1) + jnp.eye(n, k, dtype=lu_mat.dtype)
    U = jnp.triu(lu_mat[..., :k, :])
    # pivots (1-based sequential row swaps) → permutation. Concrete
    # pivots (the usual case) resolve host-side; traced pivots go through
    # a fori_loop so the jaxpr stays O(1) ops, not O(n) unrolled swaps.
    import numpy as _np

    try:
        piv = _np.asarray(lu_pivots) - 1
        perm = _np.arange(n)
        for i in range(piv.shape[-1]):
            perm[[i, piv[i]]] = perm[[piv[i], i]]
        perm = jnp.asarray(perm)
    except Exception:  # tracer (jit/vmap)
        from jax import lax as _lax

        pivj = jnp.asarray(lu_pivots) - 1

        def _swap(i, perm):
            j = pivj[i]
            pi, pj = perm[i], perm[j]
            return perm.at[i].set(pj).at[j].set(pi)

        perm = _lax.fori_loop(0, pivj.shape[-1], _swap, jnp.arange(n))
    P = jnp.eye(n, dtype=lu_mat.dtype)[perm].T
    return P, L, U


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    """Parity: paddle.linalg.cov (ddof bool → 1 or 0)."""
    return jnp.cov(
        _v(x), rowvar=rowvar, ddof=1 if ddof else 0,
        fweights=fweights, aweights=aweights,
    )


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(_v(x), rowvar=rowvar)


def multi_dot(tensors):
    return jnp.linalg.multi_dot([_v(t) for t in tensors])


def matrix_exp(x):
    import jax.scipy.linalg as jsl

    return jsl.expm(_v(x))


def svdvals(x):
    return jnp.linalg.svd(_v(x), compute_uv=False)


def vector_norm(x, p=2.0, axis=None, keepdim=False):
    x = _v(x)
    if axis is None:
        out = jnp.linalg.norm(x.reshape(-1), ord=p)
        return out.reshape((1,) * x.ndim) if keepdim else out
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(_v(x), ord=p, axis=tuple(axis), keepdims=keepdim)


def matrix_transpose(x):
    return jnp.swapaxes(_v(x), -1, -2)


def householder_product(x, tau):
    """Parity: paddle.linalg.householder_product (LAPACK orgqr)."""
    from jax.lax import linalg as lax_linalg

    return lax_linalg.householder_product(_v(x), _v(tau))


def svd_lowrank(x, q=6, niter=2, M=None):
    """Parity: paddle.linalg.svd_lowrank — randomized range finder with
    ``niter`` subspace iterations (Halko et al.), the same algorithm the
    reference wraps. Deterministic: the projection uses a fixed-seed
    gaussian (jax PRNG; no global RNG state to vary)."""
    import jax

    a = _v(x)
    if M is not None:
        a = a - _v(M)
    m, n = a.shape[-2], a.shape[-1]
    q = min(q, m, n)
    key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, a.shape[:-2] + (n, q), a.dtype)
    y = a @ omega
    for _ in range(niter):
        y = a @ (jnp.swapaxes(a, -1, -2) @ y)
    Q, _ = jnp.linalg.qr(y)
    b = jnp.swapaxes(Q, -1, -2) @ a
    u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return Q @ u_b, s, jnp.swapaxes(vt, -1, -2)


def pca_lowrank(x, q=None, center=True, niter=2):
    """Parity: paddle.linalg.pca_lowrank."""
    a = _v(x)
    m, n = a.shape[-2], a.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    return svd_lowrank(a, q=q, niter=niter)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    """Parity: paddle.linalg.cov."""
    import jax.numpy as jnp

    return jnp.cov(_v(x), rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=None if fweights is None else _v(fweights),
                   aweights=None if aweights is None else _v(aweights))


def corrcoef(x, rowvar=True, name=None):
    """Parity: paddle.linalg.corrcoef."""
    import jax.numpy as jnp

    return jnp.corrcoef(_v(x), rowvar=rowvar)
