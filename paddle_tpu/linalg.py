"""paddle.linalg parity surface over jnp.linalg."""

from __future__ import annotations

import jax.numpy as jnp

from .core.parameter import Parameter


def _v(x):
    return x.value if isinstance(x, Parameter) else x


def matmul(x, y, transpose_x=False, transpose_y=False):
    x, y = _v(x), _v(y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def norm(x, p="fro", axis=None, keepdim=False):
    """Paddle semantics (axis=None flattens any rank; int axis → vector
    p-norm; tuple axis → matrix norm). Shares the tensor.norm impl."""
    from . import tensor as _tensor

    return _tensor.norm(_v(x), p=p, axis=axis, keepdim=keepdim)


def inv(x):
    return jnp.linalg.inv(_v(x))


def pinv(x, rcond=1e-15):
    return jnp.linalg.pinv(_v(x), rcond)


def det(x):
    return jnp.linalg.det(_v(x))


def slogdet(x):
    return jnp.linalg.slogdet(_v(x))


def svd(x, full_matrices=False):
    return jnp.linalg.svd(_v(x), full_matrices=full_matrices)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(_v(x), mode=mode)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(_v(x), UPLO=UPLO)


def eig(x):
    return jnp.linalg.eig(_v(x))


def cholesky(x, upper=False):
    out = jnp.linalg.cholesky(_v(x))
    return jnp.swapaxes(out, -1, -2) if upper else out


def solve(a, b):
    return jnp.linalg.solve(_v(a), _v(b))


def lstsq(a, b, rcond=None):
    return jnp.linalg.lstsq(_v(a), _v(b), rcond=rcond)


def matrix_rank(x, tol=None):
    return jnp.linalg.matrix_rank(_v(x), tol)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(_v(x), n)


def cond(x, p=None):
    return jnp.linalg.cond(_v(x), p)


def triangular_solve(a, b, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl

    return jsl.solve_triangular(
        _v(a), _v(b), lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular,
    )
