"""Trace-time collective accounting.

``distributed/collective.py``'s in-jit collectives call ``record`` while
JAX is TRACING, so each entry reflects one collective op baked into one
compiled program — per call-site (op, axis, payload bytes). That makes
a compiled program's communication volume queryable (the per-phase
accounting kernel-attribution work assumes) without touching runtime:
re-executions of a cached program add nothing, exactly like the HLO
itself.

Bytes are the *input payload* of the collective at the trace shape
(per-participant); multiply by the axis size for ring volume as needed.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Tuple

from .registry import enabled, get_registry

_lock = threading.Lock()
# (op, axis, site) -> [n_traced_calls, total_bytes]
_log: Dict[Tuple[str, str, str], List[float]] = {}

_SKIP_DIRS = (
    os.path.join("paddle_tpu", "distributed"),
    os.path.join("paddle_tpu", "observability"),
    os.sep + "jax" + os.sep,
    os.sep + "jax_compat.py",
    "functools.py",
    "contextlib.py",
)


def _call_site() -> str:
    """First stack frame outside the collective/observability plumbing —
    the user code that asked for the collective."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not any(s in fn for s in _SKIP_DIRS):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def record(op: str, axis: str, x) -> None:
    """Account one traced collective: ``x`` is the (possibly traced)
    input array — only its aval (shape/dtype) is read."""
    if not enabled():
        return
    try:
        import numpy as np

        nbytes = int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
    except Exception:
        return
    site = _call_site()
    key = (op, str(axis), site)
    with _lock:
        ent = _log.get(key)
        if ent is None:
            _log[key] = [1, nbytes]
        else:
            ent[0] += 1
            ent[1] += nbytes
    reg = get_registry()
    reg.counter("pt_collective_traced_calls_total",
                "collective ops traced into compiled programs",
                labels=("op", "axis")).inc(op=op, axis=str(axis))
    reg.counter("pt_collective_traced_bytes_total",
                "per-participant payload bytes of traced collectives",
                labels=("op", "axis")).inc(nbytes, op=op, axis=str(axis))


def comm_log() -> List[dict]:
    """Queryable per-call-site communication table."""
    with _lock:
        items = sorted(_log.items())
    return [
        {"op": op, "axis": axis, "site": site,
         "traced_calls": int(n), "bytes": int(b)}
        for (op, axis, site), (n, b) in items
    ]


def reset_comm_log() -> None:
    with _lock:
        _log.clear()
