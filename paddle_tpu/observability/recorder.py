"""Flight recorder + anomaly watchdog — the postmortem artifact.

A ring buffer holds the last K step records (step index, wall time,
loss / grad-norm / memory when sampled). When the watchdog sees a
NaN/Inf loss or a grad-norm spike it dumps the whole window to a JSON
file, so a blown-up run leaves evidence of the steps that led into the
anomaly instead of just a stack trace. Dumps also attach the tail of
every live lifecycle tracer (``tracing.recent_events``) — when a
serving engine shares the process, the dump shows what the engine was
DOING around the anomaly (which programs ran, which requests moved),
not just metric values.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Optional

from .registry import get_registry


class FlightRecorder:
    """Ring buffer of the last ``capacity`` step records, dumpable to
    JSON. Records are plain dicts of JSON-serializable host values —
    recording never touches device state. ``trace_tail`` bounds how
    many lifecycle-tracer events a dump attaches (0 disables)."""

    def __init__(self, capacity: int = 64,
                 dump_dir: str = "flight_records",
                 trace_tail: int = 64):
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.trace_tail = int(trace_tail)
        self._buf: deque = deque(maxlen=self.capacity)
        self._n_dumps = 0

    def record(self, **fields):
        self._buf.append(fields)

    def records(self):
        return list(self._buf)

    def __len__(self):
        return len(self._buf)

    def dump(self, reason: str, extra: Optional[dict] = None) -> str:
        """Write the current window to ``dump_dir`` and return the
        path. Never raises — a failing dump must not take down the
        training loop it is documenting."""
        os.makedirs(self.dump_dir, exist_ok=True)
        self._n_dumps += 1
        path = os.path.join(
            self.dump_dir,
            f"flight_{int(time.time())}_{self._n_dumps:03d}.json")
        payload = {
            "reason": reason,
            "unix_time": time.time(),
            "n_records": len(self._buf),
            "capacity": self.capacity,
            "records": list(self._buf),
        }
        if extra:
            payload["extra"] = extra
        if self.trace_tail > 0:
            # last N request spans / step events across every live
            # tracer: the anomaly dump shows what the engine was doing,
            # not just metric values (empty when no tracer exists —
            # training-only processes pay nothing)
            from .tracing import recent_events

            tail = recent_events(self.trace_tail)
            if tail:
                payload["trace_tail"] = tail
        try:
            with open(path, "w") as f:
                json.dump(payload, f, indent=1, default=str)
        except OSError:
            return ""
        get_registry().counter(
            "pt_flight_dumps_total",
            "flight-recorder JSON dumps written").inc()
        return path


class AnomalyWatchdog:
    """Checks sampled step stats and triggers a flight-recorder dump on
    NaN/Inf loss or a grad-norm spike (> ``spike_factor`` x the running
    median of recent finite grad norms)."""

    def __init__(self, recorder: FlightRecorder,
                 spike_factor: float = 10.0,
                 history: int = 32, min_history: int = 5):
        self.recorder = recorder
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self._norms: deque = deque(maxlen=int(history))
        self.tripped: list = []  # (step, reason, dump_path)

    def _median(self) -> Optional[float]:
        if len(self._norms) < self.min_history:
            return None
        vals = sorted(self._norms)
        return vals[len(vals) // 2]

    def check(self, step: int, loss: Optional[float],
              grad_norm: Optional[float]) -> Optional[str]:
        """Returns the dump path when an anomaly fired, else None."""
        reason = None
        if loss is not None and not math.isfinite(loss):
            reason = f"non-finite loss {loss} at step {step}"
        elif grad_norm is not None and not math.isfinite(grad_norm):
            reason = f"non-finite grad norm {grad_norm} at step {step}"
        elif grad_norm is not None:
            med = self._median()
            if med is not None and med > 0 and \
                    grad_norm > self.spike_factor * med:
                reason = (f"grad-norm spike {grad_norm:.4g} > "
                          f"{self.spike_factor:g}x median {med:.4g} "
                          f"at step {step}")
        if grad_norm is not None and math.isfinite(grad_norm):
            self._norms.append(grad_norm)
        if reason is None:
            return None
        get_registry().counter(
            "pt_train_anomalies_total",
            "anomaly-watchdog trips (NaN/Inf loss, grad spikes)").inc()
        path = self.recorder.dump(reason)
        self.tripped.append((step, reason, path))
        return path
