"""Process-wide metrics registry — labeled Counter / Gauge / Histogram
with Prometheus text exposition and JSON snapshot.

Parity intent: the reference ships ad-hoc stat surfaces (benchmark/
profiler timers, fleet metric hooks, FastDeploy serving stats); this
module is the single always-on registry the trainer, the serving engine,
the collectives and the hapi callbacks all publish through, so one
``/metrics`` scrape or ``observability.dump`` sees the whole process.

Design rules:
  * ``PT_FLAGS_telemetry=off`` makes every instrumented call a true
    no-op: ``get_registry()`` hands back a shared null registry whose
    metric objects have empty-body methods — no label-dict churn, no
    locks, no allocation on the hot path.
  * Histograms use FIXED exponential bucket edges (Prometheus
    cumulative-``le`` convention) plus a small bounded window of raw
    observations for accurate local percentiles (p50/p90 in
    ``metrics_snapshot()`` without bucket interpolation error).
  * Thread-safe: one registry-wide rlock guards series creation and
    updates (admission threads, HTTP scrape thread, train loop).
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Optional, Sequence, Tuple

from .. import flags


def exp_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """``count`` exponentially spaced upper edges: start * factor**i."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError(
            f"exp_buckets needs start>0, factor>1, count>=1; got "
            f"({start}, {factor}, {count})")
    return tuple(start * factor ** i for i in range(count))


# default edges suit millisecond-scale latencies: 1ms .. ~65s
DEFAULT_BUCKETS = exp_buckets(1.0, 2.0, 17)

# raw-observation window per histogram series (for exact percentiles)
_WINDOW = 2048


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integral floats print as ints."""
    f = float(v)
    if math.isfinite(f) and f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str],
                 lock: threading.RLock):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = lock
        self._series: Dict[Tuple, object] = {}

    def _key(self, labels: Dict[str, object]) -> Tuple:
        if tuple(sorted(labels)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"metric {self.name!r} declared labels "
                f"{self.label_names}, got {tuple(sorted(labels))}")
        return tuple(str(labels[k]) for k in self.label_names)

    def _label_str(self, key: Tuple) -> str:
        if not self.label_names:
            return ""
        pairs = ",".join(
            f'{n}="{_escape(v)}"' for n, v in zip(self.label_names, key))
        return "{" + pairs + "}"

    def series(self):
        with self._lock:
            return dict(self._series)


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels):
        if value < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        k = self._key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value

    def value(self, **labels) -> float:
        return float(self._series.get(self._key(labels), 0.0))

    def expose(self, lines):
        for k, v in sorted(self.series().items()):
            lines.append(f"{self.name}{self._label_str(k)} {_fmt(v)}")

    def snap(self):
        return [{"labels": dict(zip(self.label_names, k)), "value": v}
                for k, v in sorted(self.series().items())]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            self._series[self._key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels):
        k = self._key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value

    def dec(self, value: float = 1.0, **labels):
        self.inc(-value, **labels)

    def set_max(self, value: float, **labels):
        """Peak-tracking write: keeps the running maximum."""
        k = self._key(labels)
        with self._lock:
            cur = self._series.get(k)
            if cur is None or value > cur:
                self._series[k] = float(value)

    def value(self, **labels) -> float:
        return float(self._series.get(self._key(labels), 0.0))

    expose = Counter.expose
    snap = Counter.snap


class _HistSeries:
    __slots__ = ("counts", "sum", "count", "window")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.window = deque(maxlen=_WINDOW)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, label_names, lock,
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_, label_names, lock)
        edges = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
        if list(edges) != sorted(edges) or len(set(edges)) != len(edges):
            raise ValueError(
                f"histogram {name!r} bucket edges must be strictly "
                f"increasing: {edges}")
        self.buckets = edges

    def _get(self, labels) -> _HistSeries:
        k = self._key(labels)
        s = self._series.get(k)
        if s is None:
            s = self._series.setdefault(k, _HistSeries(len(self.buckets)))
        return s

    def observe(self, value: float, **labels):
        v = float(value)
        with self._lock:
            s = self._get(labels)
            i = len(self.buckets)
            for j, edge in enumerate(self.buckets):
                if v <= edge:
                    i = j
                    break
            s.counts[i] += 1
            s.sum += v
            s.count += 1
            s.window.append(v)

    def count(self, **labels) -> int:
        s = self._series.get(self._key(labels))
        return s.count if s else 0

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Exact percentile over the recent raw-observation window
        (q in [0, 100]); None with no observations."""
        with self._lock:
            s = self._series.get(self._key(labels))
            if not s or not s.window:
                return None
            vals = sorted(s.window)
        idx = min(len(vals) - 1, max(0, int(round(
            q / 100.0 * (len(vals) - 1)))))
        return vals[idx]

    def window_len(self, **labels) -> int:
        """Observations currently in the raw percentile window."""
        with self._lock:
            s = self._series.get(self._key(labels))
            return len(s.window) if s else 0

    def reset_window(self, **labels):
        """Clear the raw percentile window for one series; cumulative
        bucket counts / sum / count are untouched (Prometheus totals
        must never go backwards)."""
        with self._lock:
            s = self._series.get(self._key(labels))
            if s:
                s.window.clear()

    def expose(self, lines):
        for k, s in sorted(self.series().items()):
            cum = 0
            for edge, c in zip(self.buckets, s.counts):
                cum += c
                labels = list(zip(self.label_names, k)) + [("le", _fmt(edge))]
                pairs = ",".join(
                    f'{n}="{_escape(v)}"' for n, v in labels)
                lines.append(f"{self.name}_bucket{{{pairs}}} {cum}")
            cum += s.counts[-1]
            pairs = ",".join(
                f'{n}="{_escape(v)}"'
                for n, v in list(zip(self.label_names, k)) + [("le", "+Inf")])
            lines.append(f"{self.name}_bucket{{{pairs}}} {cum}")
            ls = self._label_str(k)
            lines.append(f"{self.name}_sum{ls} {_fmt(s.sum)}")
            lines.append(f"{self.name}_count{ls} {s.count}")

    def snap(self):
        out = []
        for k, s in sorted(self.series().items()):
            out.append({
                "labels": dict(zip(self.label_names, k)),
                "count": s.count,
                "sum": s.sum,
                "buckets": {_fmt(e): c
                            for e, c in zip(self.buckets, s.counts)},
                "inf": s.counts[-1],
                "p50": self.percentile(50, **dict(zip(self.label_names, k))),
                "p90": self.percentile(90, **dict(zip(self.label_names, k))),
            })
        return out


class MetricsRegistry:
    """Named metrics with get-or-create semantics (idempotent across the
    many modules that instrument the same process)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help_, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or \
                        m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} re-registered as {cls.kind} "
                        f"labels={tuple(labels)}; existing is {m.kind} "
                        f"labels={m.label_names}")
                # buckets=None on re-registration means "fetch whatever
                # exists" (the common re-fetch idiom); only an EXPLICIT
                # conflicting edge set is an error
                want = kw.get("buckets")
                if want is not None and \
                        tuple(want) != tuple(getattr(m, "buckets", ())):
                    raise ValueError(
                        f"histogram {name!r} re-registered with "
                        f"different buckets {tuple(want)}; existing "
                        f"{tuple(m.buckets)} — observations would land "
                        "in the first caller's edges")
                return m
            m = cls(name, help_, labels, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help_: str = "", labels: Sequence[str] = ()):
        return self._get_or_create(Counter, name, help_, labels)

    def gauge(self, name, help_: str = "", labels: Sequence[str] = ()):
        return self._get_or_create(Gauge, name, help_, labels)

    def histogram(self, name, help_: str = "", labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None):
        return self._get_or_create(Histogram, name, help_, labels,
                                   buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    # ---------------- exposition ----------------
    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            m.expose(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-ready snapshot of every metric."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return {
            m.name: {"type": m.kind, "help": m.help, "series": m.snap()}
            for m in metrics
        }


# ---------------------------------------------------------------------------
# null objects — what instrumented code holds when telemetry is off
# ---------------------------------------------------------------------------
class _NullMetric:
    """Shared do-nothing stand-in for every metric kind."""

    def inc(self, *a, **k):
        pass

    dec = set = set_max = observe = inc

    def value(self, **k):
        return 0.0

    def count(self, **k):
        return 0

    window_len = count

    def percentile(self, q, **k):
        return None

    def reset_window(self, **k):
        pass

    def series(self):
        return {}


_NULL_METRIC = _NullMetric()


class NullRegistry:
    def counter(self, *a, **k):
        return _NULL_METRIC

    gauge = histogram = counter

    def get(self, name):
        return None

    def reset(self):
        pass

    def prometheus_text(self):
        return ""

    def snapshot(self):
        return {}


_GLOBAL = MetricsRegistry()
_NULL = NullRegistry()


def enabled() -> bool:
    return bool(flags.flag("telemetry"))


def get_registry():
    """The process-wide registry, or the shared null registry when
    ``PT_FLAGS_telemetry=off`` (instrumented paths become no-ops)."""
    return _GLOBAL if enabled() else _NULL


def global_registry() -> MetricsRegistry:
    """The real registry regardless of the flag (exposition/tests)."""
    return _GLOBAL
