"""Always-on runtime telemetry.

One process-wide ``MetricsRegistry`` (Counter / Gauge / Histogram with
Prometheus text exposition and JSON snapshot) that the trainer, the
continuous-batching engine, the collectives and the hapi callbacks all
publish through; a flight recorder + anomaly watchdog for postmortems;
and a one-shot dump CLI (``python -m paddle_tpu.observability.dump``).

``PT_FLAGS_telemetry=off`` turns every instrumented path into a true
no-op (shared null objects, no dict churn). See README "Observability".
"""

from . import alerts, profiling, timeseries, tracing  # noqa: F401
from .alerts import ALERT_RULES, AlertManager, AlertRule  # noqa: F401
from .comm import comm_log, record as record_collective, reset_comm_log  # noqa: F401
from .timeseries import TimeSeriesStore  # noqa: F401
from .profiling import (  # noqa: F401
    PROGRAM_LABELS,
    ProgramProfiler,
    RecompileWatchdog,
    hbm_accounting,
)
from .recorder import AnomalyWatchdog, FlightRecorder  # noqa: F401
from .tracing import Tracer  # noqa: F401
from .registry import (  # noqa: F401
    Counter,
    DEFAULT_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    enabled,
    exp_buckets,
    get_registry,
    global_registry,
)
from .serve import RouterTelemetry, ServingTelemetry  # noqa: F401
from .train import TrainTelemetry, record_scalars  # noqa: F401
