"""Measured per-program device-time attribution for the serving engine.

Every serving perf claim since the fused-decode PR has been verified by
MODELED HBM bytes and compile counts; this module is the measurement
layer that lets those models be laid against reality:

* :class:`ProgramProfiler` — cadence-sampled ``block_until_ready``
  timing around every compiled serving dispatch. A SAMPLED dispatch
  records the measured three-way decomposition

      schedule_ms  host work before the jit call (COW checks, sampling
                   vectors, array staging)
      dispatch_ms  the jit call itself (cache lookup + async dispatch;
                   a compile lands here)
      device_ms    dispatch-done → ``block_until_ready`` on the
                   program's own outputs — MEASURED device wall, not
                   the dispatch-to-token-sync estimate the tracer's
                   ``sync_wall_ms`` field falls back to

  into ``pt_serve_program_ms{engine,program}`` (plus dispatch/schedule
  histograms) and host-side stats that survive telemetry=off.
  UNSAMPLED dispatches stay fully async: the engine's seams consult
  ``want()`` (one int increment) and never sync — the PR-2 cadence
  discipline. With ``PT_FLAGS_profile_programs`` off the engine holds
  no profiler at all (one identity check per seam, zero new compiled
  programs — pinned by test).

* :class:`RecompileWatchdog` — seals the expected compiled-program set
  after warmup and, on any post-seal ``TRACE_COUNTS`` growth during
  one of the OWNING engine's own ticks, counts
  ``pt_serve_recompiles_total{engine,program}`` and dumps a
  FlightRecorder artifact carrying the offending specialization's arg
  shapes (``TRACE_SHAPES``, recorded at trace time). The production
  complement to ptlint TS003 (jit-wrapper-in-loop) and the test-only
  ``compile_counter`` guards: those catch recompiles in CI workloads,
  this catches them in live traffic. Tick-scoped diffs keep engines in
  one process from blaming each other's warmup compiles.

* :func:`hbm_accounting` — live HBM residency derived from the pools
  the engine already owns (array ``nbytes`` metadata — no device
  traffic): KV pool bytes including int8 scale rows, weight/buffer
  bytes by dtype, contiguous prefix-store bytes.

``PROGRAM_LABELS`` is the attribution registry ptlint's OBS001 rule
checks for completeness: every ``TRACE_COUNTS``-registered program name
must carry a timing label here, so a new compiled program cannot ship
without joining the attribution surface.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

from .. import flags
from .registry import exp_buckets, get_registry

# ---------------------------------------------------------------------------
# the attribution registry (ptlint OBS001: every TRACE_COUNTS program
# name must appear here — keep the keys in sync with the compile
# counters in inference/serving.py; the runtime twin of the static rule
# lives in tests/test_profiling.py)
# ---------------------------------------------------------------------------
PROGRAM_LABELS: Dict[str, str] = {
    "prefill_chunk": "fixed [slots, C] chunked prefill (THE prefill "
                     "program; one call per suffix chunk of a wave)",
    "prefill_bucket": "legacy per-bucket whole-prompt prefill (the "
                      "parity oracle; one specialization per bucket)",
    "prefill_insert": "legacy contiguous prefill cache insert "
                      "(dynamic_update_slice into the slot rows)",
    "prefill_scatter": "legacy paged prefill page scatter (bucket "
                       "cache -> the slot's pages)",
    "prefix_insert": "contiguous prefix-cache block insert (cached "
                     "K/V block -> a slot's rows)",
    "prefix_read": "contiguous prefix-cache block read (a slot's "
                   "rows -> the store's materialized block)",
    "page_copy": "copy-on-write page duplication across every "
                 "layer's pool (src -> dst, scales ride along)",
    "decode_step": "one-token decode over all slots ([slots, 1])",
    "decode_chunk": "K-step fused decode chunk (lax.scan; one host "
                    "sync per K tokens)",
    "spec_verify": "speculative [slots, spec_k+1] multi-token verify "
                   "pass with in-jit greedy acceptance",
}

# dispatch seams the engine actually times (the rest of the labels are
# attribution-only: trace-count registered, priced by kernelbench, but
# dispatched rarely enough that their wall rides the seams above)
TIMED_PROGRAMS = frozenset({
    "prefill_chunk", "prefill_bucket", "decode_step", "decode_chunk",
    "spec_verify", "page_copy",
})


class ProgramProfiler:
    """Per-engine cadence-sampled program timer.

    ``want(program)`` increments that program's dispatch counter and
    returns True on the sampling cadence (every Nth dispatch per
    program — deterministic, like the tracer's request thinning). The
    engine then brackets the dispatch with ``t0``/``t_call``/``t_disp``
    stamps and calls :meth:`observe`, which blocks until the program's
    own outputs are ready and records the measured decomposition.

    Host-side stats (:meth:`snapshot`) survive ``PT_FLAGS_telemetry=
    off``; the registry histograms no-op through the null registry
    then, same contract as every other serving counter.
    """

    _SEQ = 0  # fallback engine ids when telemetry is off

    def __init__(self, engine_id: Optional[str] = None,
                 sample_every: Optional[int] = None,
                 window: int = 256):
        if engine_id is None:
            engine_id = f"p{ProgramProfiler._SEQ}"
            ProgramProfiler._SEQ += 1
        self.engine_id = str(engine_id)
        if sample_every is None:
            sample_every = int(flags.flag("profile_sample_every"))
        self.sample_every = max(int(sample_every), 1)
        self._window = max(int(window), 1)
        # program -> {"dispatches", "sampled", totals, deques}
        self._stats: Dict[str, dict] = {}
        reg = get_registry()
        L = ("engine", "program")
        self._h_device = reg.histogram(
            "pt_serve_program_ms",
            "MEASURED device wall per sampled compiled-serving-program "
            "dispatch (block_until_ready on the program's own outputs "
            "— not the dispatch-to-token-sync estimate)",
            labels=L, buckets=exp_buckets(0.05, 2.0, 20))
        self._h_dispatch = reg.histogram(
            "pt_serve_program_dispatch_ms",
            "host dispatch wall per sampled dispatch (jit cache "
            "lookup + async dispatch; compiles land here)",
            labels=L, buckets=exp_buckets(0.05, 2.0, 18))
        self._h_schedule = reg.histogram(
            "pt_serve_program_schedule_ms",
            "host scheduling wall before the jit call per sampled "
            "dispatch (COW checks, sampling vectors, array staging)",
            labels=L, buckets=exp_buckets(0.05, 2.0, 18))

    def _prog(self, program: str) -> dict:
        st = self._stats.get(program)
        if st is None:
            if program not in PROGRAM_LABELS:
                raise ValueError(
                    f"unknown program {program!r} — register a timing "
                    "label in observability.profiling.PROGRAM_LABELS "
                    "(ptlint OBS001 keeps this registry complete)")
            st = self._stats[program] = {
                "dispatches": 0, "sampled": 0,
                "device_ms_total": 0.0, "device_ms_max": 0.0,
                "dispatch_ms_total": 0.0, "schedule_ms_total": 0.0,
                "win": deque(maxlen=self._window),
            }
        return st

    # ---------------- sampling ----------------
    def want(self, program: str) -> bool:
        """One dispatch of ``program``; True when THIS dispatch is on
        the sampling cadence. Cadence N samples dispatches N, 2N, ...
        — a program's first dispatch (its compile) is only sampled at
        cadence 1, so steady-state windows stay compile-free."""
        st = self._prog(program)
        st["dispatches"] += 1
        return st["dispatches"] % self.sample_every == 0

    # ---------------- measurement ----------------
    def observe(self, program: str, t0: float, t_call: float,
                t_disp: float, out) -> dict:
        """Block until ``out`` (the program's own outputs) is ready and
        record the measured decomposition. Returns the decomposition
        dict so the caller can embed it in the tracer's step event."""
        import jax

        jax.block_until_ready(out)
        t_dev = time.perf_counter()
        dec = {
            "schedule_ms": (t_call - t0) * 1e3,
            "dispatch_ms": (t_disp - t_call) * 1e3,
            "device_ms": (t_dev - t_disp) * 1e3,
        }
        st = self._prog(program)
        st["sampled"] += 1
        st["device_ms_total"] += dec["device_ms"]
        st["device_ms_max"] = max(st["device_ms_max"], dec["device_ms"])
        st["dispatch_ms_total"] += dec["dispatch_ms"]
        st["schedule_ms_total"] += dec["schedule_ms"]
        st["win"].append(dec["device_ms"])
        lab = {"engine": self.engine_id, "program": program}
        self._h_device.observe(dec["device_ms"], **lab)
        self._h_dispatch.observe(dec["dispatch_ms"], **lab)
        self._h_schedule.observe(dec["schedule_ms"], **lab)
        return dec

    # ---------------- read side ----------------
    def snapshot(self) -> dict:
        """Per-program measured stats (copy-on-read: the scrape thread
        calls this through ``engine.profile_snapshot()``)."""
        programs = {}
        for name, st in list(self._stats.items()):
            win = sorted(st["win"])  # deque snapshot -> new list
            sampled = st["sampled"]
            programs[name] = {
                "dispatches": st["dispatches"],
                "sampled": sampled,
                "device_ms_p50": (win[len(win) // 2] if win else None),
                "device_ms_mean": (st["device_ms_total"] / sampled
                                   if sampled else None),
                "device_ms_max": (st["device_ms_max"] if sampled
                                  else None),
                "dispatch_ms_mean": (st["dispatch_ms_total"] / sampled
                                     if sampled else None),
                "schedule_ms_mean": (st["schedule_ms_total"] / sampled
                                     if sampled else None),
            }
        return {
            "engine": self.engine_id,
            "sample_every": self.sample_every,
            "programs": programs,
        }

    def window_reset(self):
        """Zero the host-side stats — one measurement window per bench
        sweep (registry histogram totals keep running, same contract
        as ``metrics_window_reset``)."""
        self._stats = {}


class RecompileWatchdog:
    """Seal-then-watch guard over the trace-time compile counters.

    The owning engine calls ``tick_begin()``/``tick_end()`` around each
    scheduler tick. Pre-seal, ticks just count toward
    ``warmup_ticks`` (compiles are expected while programs warm up);
    once sealed — by the tick budget or an explicit :meth:`seal` —
    every tick snapshots the counters at entry and diffs at exit, so
    growth is attributed to THIS engine's own tick (two engines in one
    process never blame each other's warmup). A detected recompile
    increments host + registry counters and (telemetry on) dumps a
    FlightRecorder artifact with the offending program's trace-time
    arg shapes. It never raises: production keeps serving; the strict
    fail-on-recompile contract stays with the test-only
    ``compile_counter`` guards.
    """

    def __init__(self, counts, shapes, engine_id: str = "0",
                 warmup_ticks: Optional[int] = None,
                 dump: bool = True):
        """``counts``/``shapes``: the serving module's ``TRACE_COUNTS``
        / ``TRACE_SHAPES`` mappings (passed in — observability must not
        import the inference package)."""
        self._counts = counts
        self._shapes = shapes
        self.engine_id = str(engine_id)
        if warmup_ticks is None:
            warmup_ticks = int(flags.flag("recompile_warmup_ticks"))
        self.warmup_ticks = max(int(warmup_ticks), 0)
        self._dump = bool(dump)
        self._ticks = 0
        self.sealed = False
        self._base: Optional[Dict[str, int]] = None
        self.recompiles: Dict[str, int] = {}
        self._recorder = None
        self._counter = get_registry().counter(
            "pt_serve_recompiles_total",
            "post-seal jit re-specializations of a compiled serving "
            "program detected by the runtime recompile watchdog "
            "(TRACE_COUNTS growth during one of the owning engine's "
            "own ticks) — each one also leaves a FlightRecorder "
            "artifact naming the offending arg shapes",
            ("engine", "program"))

    def seal(self):
        """Seal the expected program set NOW (e.g. right after a bench
        warmup) — later compiles are recompiles."""
        self.sealed = True

    # ---------------- tick hooks ----------------
    def tick_begin(self):
        if not self.sealed:
            self._ticks += 1
            if self._ticks >= self.warmup_ticks:
                self.sealed = True
            return
        self._base = dict(self._counts)

    def tick_end(self) -> List[str]:
        """Diff this tick's compile counters; returns the programs
        that re-specialized (empty pre-seal)."""
        base = self._base
        if base is None:
            return []
        self._base = None
        grown = {k: v - base.get(k, 0)
                 for k, v in list(self._counts.items())
                 if v > base.get(k, 0)}
        for program, n in grown.items():
            # count by the DELTA: one tick can re-specialize a
            # program several times (e.g. two never-seen buckets in
            # one admission wave). The shape artifact names the most
            # recent specialization only — TRACE_SHAPES holds one
            # note per program by design.
            first = program not in self.recompiles
            self.recompiles[program] = \
                self.recompiles.get(program, 0) + n
            self._counter.inc(n, engine=self.engine_id,
                              program=program)
            if first:
                # ONE artifact per program per watchdog: counters keep
                # counting, but sustained legitimate specialization
                # after an undersized warmup (e.g. legacy bucketed
                # prefill meeting a new bucket, the first COW
                # compiling page_copy late) must not fill the dump
                # dir with a file per tick
                self._dump_artifact(program)
        return list(grown)

    def _dump_artifact(self, program: str):
        """FlightRecorder postmortem: which program re-specialized,
        with the arg shapes its trace-time shape note recorded — the
        evidence a shape-drift bug needs. Telemetry off = counters
        only (same gate as the engine's NaN dumps)."""
        from .registry import enabled

        if not self._dump or not enabled():
            return
        if self._recorder is None:
            from .recorder import FlightRecorder

            self._recorder = FlightRecorder(
                capacity=int(flags.flag("telemetry_flight_window")),
                dump_dir=str(flags.flag("telemetry_dump_dir")))
        self._recorder.record(
            kind="serve_recompile", program=program,
            engine=self.engine_id,
            count=int(self._counts.get(program, 0)),
            arg_shapes=dict(self._shapes.get(program) or {}))
        self._recorder.dump(
            f"post-seal recompile of serving program {program!r} "
            f"(engine {self.engine_id}) — arg shapes attached")

    # ---------------- read side ----------------
    def snapshot(self) -> dict:
        return {
            "enabled": True,
            "sealed": self.sealed,
            "warmup_ticks": self.warmup_ticks,
            "ticks": self._ticks,
            "recompiles": {k: v for k, v
                           in list(self.recompiles.items())},
        }


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------
def _nbytes(arr) -> int:
    nb = getattr(arr, "nbytes", None)
    return int(nb) if nb is not None else 0


def weight_bytes_by_dtype(*sources) -> Dict[str, int]:
    """``weights_<dtype>`` → bytes over param/buffer mappings. The
    model tree is immutable after engine init, so the engine computes
    this ONCE and caches it (``engine._hbm_weights``) — a profiler-
    sampled dispatch must not re-walk hundreds of leaves per sample."""
    out: Dict[str, int] = {}
    for src in sources:
        for v in list(src.values()):
            dt = str(getattr(v, "dtype", "unknown"))
            key = f"weights_{dt}"
            out[key] = out.get(key, 0) + _nbytes(v)
    return out


def hbm_accounting(engine) -> Dict[str, int]:
    """Component → bytes for the device memory the engine owns, from
    array ``nbytes`` METADATA only (no device traffic, scrape-thread
    safe):

      * ``kv_pool`` — the KV cache payload (paged pools or contiguous
        caches; int8 quantized payloads count at their int8 width);
      * ``kv_scales`` — the int8 pools' per-row f32 dequant scales
        (0 for float caches);
      * ``weights_<dtype>`` — model params + buffers grouped by dtype
        (int8/int4 qweights and their f32 group scales land in their
        own rows — the quantized-serving residency split);
      * ``prefix_store`` — the CONTIGUOUS prefix store's materialized
        blocks (real device memory on top of the engine's own cache;
        the paged store refcounts pool pages and owns no extra bytes).
    """
    from ..inference.paged import QuantizedKV

    out: Dict[str, int] = {"kv_pool": 0, "kv_scales": 0,
                           "prefix_store": 0}

    def kv_leaf(x):
        if isinstance(x, QuantizedKV):
            out["kv_pool"] += _nbytes(x.q)
            out["kv_scales"] += _nbytes(x.scale)
        else:
            out["kv_pool"] += _nbytes(x)

    if engine.cfg.paged:
        for c in list(engine.layer_caches):
            out["kv_pool"] += _nbytes(c.k_pages) + _nbytes(c.v_pages)
            if getattr(c, "k_scale", None) is not None:
                out["kv_scales"] += _nbytes(c.k_scale)
                out["kv_scales"] += _nbytes(c.v_scale)
    else:
        for k, v in list(engine.caches):
            kv_leaf(k)
            kv_leaf(v)
        store = engine._prefix
        if store is not None:
            # entries are (k, v, namespace) — the tenant namespace is
            # bookkeeping, not HBM
            for kb, vb, *_ns in list(
                    getattr(store, "_blocks", {}).values()):
                kv = 0
                for blk in (kb, vb):
                    if isinstance(blk, QuantizedKV):
                        kv += _nbytes(blk.q) + _nbytes(blk.scale)
                    else:
                        kv += _nbytes(blk)
                out["prefix_store"] += kv
    static = getattr(engine, "_hbm_weights", None)
    if static is None:
        static = weight_bytes_by_dtype(engine.params, engine.buffers)
    out.update(static)
    return out
