"""Bounded fixed-cadence time-series history over serving metrics.

PRs 2/6/12 built point-in-time observability — registry snapshots,
lifecycle traces, measured per-program device time — but nothing in the
stack remembered *history*: an operator (or the degradation ladder)
could not ask "is TTFT attainment burning down?" or "did the prefix
hit-rate collapse when that tenant arrived?". This module is the
flight-data recorder those questions read:

* :class:`TimeSeriesStore` — a bounded ring of WINDOWED samples. The
  owning engine (or router) calls :meth:`on_tick` once per scheduler
  tick with a collector callable; every ``cadence``-th tick the window
  closes: the collector's cumulative counters become per-window DELTAS
  and per-tick RATES, gauges are point-sampled, and (telemetry on)
  histogram window-percentiles ride along. The ring keeps the last
  ``retention`` windows — host memory is bounded no matter how long the
  engine runs.

* **Tick-driven, wall-clock-free in all decisions**: window boundaries,
  deltas and rates are functions of tick counts only (the same
  determinism contract as the breaker/ladder state machines — replaying
  the same tick sequence reproduces the same series, which is what
  makes the alert layer's firings deterministic under seeded fault
  storms). ``perf_counter`` stamps ride along on each sample for
  display/correlation only; nothing decides on them.

* **Scrape-thread-safe copy-on-read**: samples are built fully before
  being appended under a lock and never mutated afterwards; readers
  (:meth:`series` / :meth:`snapshot`) take the lock and return fresh
  lists — the CC001/SAFE_READS contract every other serving reader
  follows. ``engine.timeline_snapshot()`` / the ``/timeline`` endpoint /
  ``dump --timeline`` all read through here.

Gating: ``PT_FLAGS_timeseries`` (off = the engine holds ``None`` — one
identity check per tick, zero allocation, zero new compiled programs,
outputs bit-identical; pinned by test), with ``timeseries_cadence`` /
``timeseries_retention`` sizing the windows.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

from .. import flags

# live stores (weak: an engine dropping its store drops it here too) —
# the `dump --timeline` CLI reads the process-wide view, mirroring the
# tracer registry in tracing.py
_STORES: "weakref.WeakSet[TimeSeriesStore]" = weakref.WeakSet()

_LABEL_SEQ = itertools.count()


def stores() -> List["TimeSeriesStore"]:
    """Every live store in the process (weak registry) — the
    ``dump --timeline`` export path."""
    return list(_STORES)


class TimeSeriesStore:
    """Fixed-cadence windowed metric history for ONE engine or router.

    ``collect`` (passed to :meth:`on_tick`) returns the current
    cumulative view::

        {"counters": {name: cumulative float},   # deltas/rates derived
         "gauges":   {name: current float},      # point-sampled
         "percentiles": {name: float | None}}    # histogram windows

    Counter keys may carry a per-class suffix (``"slo_met:interactive"``)
    — the alert rules parse the prefix. Each closed window appends one
    immutable sample dict::

        {"tick", "window_ticks", "t", "wall_s",
         "counters", "deltas", "rates", "gauges", "percentiles"}

    where ``rates`` are per-TICK (delta / window_ticks — deterministic;
    divide by ``wall_s`` for a per-second display rate, which nothing in
    the alert layer does).
    """

    def __init__(self, label: Optional[str] = None,
                 cadence: Optional[int] = None,
                 retention: Optional[int] = None):
        if label is None:
            label = f"ts{next(_LABEL_SEQ)}"
        self.label = str(label)
        if cadence is None:
            cadence = int(flags.flag("timeseries_cadence"))
        if retention is None:
            retention = int(flags.flag("timeseries_retention"))
        self.cadence = max(int(cadence), 1)
        self.retention = max(int(retention), 1)
        self._ring: deque = deque(maxlen=self.retention)
        self._lock = threading.Lock()
        self._tick = 0
        # previous window's cumulative counters ({} at start: the first
        # window's deltas are the full counts — counters start at zero
        # when the engine that owns this store is constructed)
        self._last: Dict[str, float] = {}
        self._t_last: Optional[float] = None
        _STORES.add(self)

    # ---------------- write side (scheduler thread) ----------------
    def on_tick(self, collect: Callable[[], dict]) -> Optional[dict]:
        """Advance one scheduler tick; every ``cadence``-th tick closes
        a window (calls ``collect`` and appends the windowed sample).
        Returns the new sample, or None between window boundaries —
        the tick count is the ONLY input to that decision."""
        self._tick += 1
        if self._tick % self.cadence:
            return None
        doc = collect()
        counters = {k: float(v)
                    for k, v in doc.get("counters", {}).items()}
        # Prometheus counter-reset convention: a value BELOW the
        # previous sample means the source was reset between windows
        # (bench window resets clear slo_stats/_finished mid-run) —
        # the delta restarts from the post-reset count instead of
        # going negative and poisoning every window-aggregating rule
        deltas = {}
        for k, v in counters.items():
            last = self._last.get(k, 0.0)
            deltas[k] = v - last if v >= last else v
        rates = {k: d / self.cadence for k, d in deltas.items()}
        now = time.perf_counter()
        sample = {
            "tick": self._tick,
            "window_ticks": self.cadence,
            # display-only stamps: correlation with the tracer/registry,
            # never an input to windowing or alert decisions
            "t": now,
            "wall_s": (now - self._t_last
                       if self._t_last is not None else None),
            "counters": counters,
            "deltas": deltas,
            "rates": rates,
            "gauges": {k: float(v)
                       for k, v in doc.get("gauges", {}).items()},
            "percentiles": dict(doc.get("percentiles", {})),
        }
        self._last = counters
        self._t_last = now
        with self._lock:
            self._ring.append(sample)
        return sample

    # ---------------- read side (any thread) ----------------
    def series(self) -> List[dict]:
        """Snapshot copy of the ring, oldest first. Samples are
        immutable after append, so handing them out by reference is
        torn-window-free; only the ring itself needs the lock."""
        with self._lock:
            return list(self._ring)

    def last(self, n: int) -> List[dict]:
        with self._lock:
            k = len(self._ring)
            return list(itertools.islice(self._ring, max(k - n, 0), k))

    def __len__(self):
        return len(self._ring)

    def snapshot(self) -> dict:
        """JSON-ready view: config + the full retained series. One
        critical section for tick/window/series, so a scrape racing a
        window close can never return a doc whose ``windows`` count
        disagrees with ``len(series)``."""
        with self._lock:
            series = list(self._ring)
            tick = self._tick
        return {
            "label": self.label,
            "cadence": self.cadence,
            "retention": self.retention,
            "ticks": tick,
            "windows": len(series),
            "series": series,
        }
