"""Serving telemetry for the continuous-batching engine.

Aggregates TTFT/TPOT histograms, queue-depth and batch-occupancy
gauges, KV-pool utilization and request/token counters. The engine
calls the ``on_*`` hooks from its scheduling loop; everything here is
host-side bookkeeping over values the scheduler already holds — no
extra device traffic.

Every metric carries an ``engine`` label (a process-monotonic id), so
two engines in one process — bench sweeps, multi-model serving — keep
distinct series on the same ``/metrics`` scrape, and one engine's
``window_reset()`` cannot clobber another's peaks.

``window_reset()`` clears the raw percentile windows (histogram-side)
and peak trackers without touching the cumulative Prometheus totals,
so a benchmark sweep (benchmarks/suite.py ``_run_load``) reads
per-window percentiles from the same registry a live scrape sees.
"""

from __future__ import annotations

import itertools
from typing import Optional

from .registry import exp_buckets, get_registry

_ENGINE_SEQ = itertools.count()


class ServingTelemetry:
    def __init__(self):
        reg = get_registry()
        self.engine_id = str(next(_ENGINE_SEQ))
        L = ("engine",)
        self._ttft = reg.histogram(
            "pt_serve_ttft_ms", "time to first token (ms)", labels=L,
            buckets=exp_buckets(1.0, 2.0, 18))
        self._tpot = reg.histogram(
            "pt_serve_tpot_ms", "time per output token (ms)", labels=L,
            buckets=exp_buckets(0.25, 2.0, 16))
        self._queue = reg.gauge(
            "pt_serve_queue_depth", "requests waiting for a slot", L)
        self._queue_peak = reg.gauge(
            "pt_serve_queue_depth_peak", "peak queue depth this window",
            L)
        self._occ = reg.gauge(
            "pt_serve_batch_occupancy", "active slots / max_slots", L)
        self._occ_peak = reg.gauge(
            "pt_serve_batch_occupancy_peak",
            "peak occupancy this window", L)
        self._kv = reg.gauge(
            "pt_serve_kv_pool_utilization",
            "KV pool occupancy (pages or cache rows in use, 0-1)", L)
        self._kv_peak = reg.gauge(
            "pt_serve_kv_pool_utilization_peak",
            "peak KV pool occupancy this window", L)
        self._kv_used = reg.gauge(
            "pt_serve_kv_pool_used", "KV pool units in use",
            ("engine", "unit"))
        self._submitted = reg.counter(
            "pt_serve_requests_submitted_total", "requests enqueued", L)
        self._admitted = reg.counter(
            "pt_serve_requests_admitted_total",
            "requests given a decode slot", L)
        self._finished = reg.counter(
            "pt_serve_requests_finished_total", "requests completed", L)
        self._tokens = reg.counter(
            "pt_serve_tokens_generated_total", "output tokens produced",
            L)
        # tenant-labeled (tenant "-" = untagged traffic): per-tenant
        # hit rates are the isolation evidence — one tenant's eviction
        # storm showing up as ANOTHER tenant's hit-rate collapse is
        # exactly what the namespace quotas exist to prevent
        LT = ("engine", "tenant")
        self._pfx_hits = reg.counter(
            "pt_serve_prefix_cache_hits_total",
            "admissions that reused a cached prompt prefix", LT)
        self._pfx_misses = reg.counter(
            "pt_serve_prefix_cache_misses_total",
            "admissions with no cached prefix", LT)
        self._pfx_hit_tokens = reg.counter(
            "pt_serve_prefix_cache_hit_tokens_total",
            "prompt tokens served from the prefix cache", LT)
        self._pfx_prompt_tokens = reg.counter(
            "pt_serve_prefix_cache_prompt_tokens_total",
            "prompt tokens submitted through prefix lookup", LT)
        self._pfx_evict = reg.counter(
            "pt_serve_prefix_cache_evictions_total",
            "prefix blocks/pages evicted (LRU)", L)
        self._pfx_cached = reg.gauge(
            "pt_serve_prefix_cached_pages",
            "prefix blocks/pages currently resident in the store", L)
        self._spec_proposed = reg.counter(
            "pt_serve_spec_proposed_tokens_total",
            "draft tokens submitted to the multi-token verify pass", L)
        self._spec_accepted = reg.counter(
            "pt_serve_spec_accepted_tokens_total",
            "draft tokens accepted by greedy verification", L)
        self._spec_verify = reg.counter(
            "pt_serve_spec_verify_calls_total",
            "batched [slots, K+1] verify dispatches", L)
        self._spec_fallback = reg.counter(
            "pt_serve_spec_fallback_steps_total",
            "spec-enabled steps where no verify pass dispatched (no "
            "slot drafted, or the chunk scheduler's drafting-share "
            "gate kept the plain chunk) — plain decode ran", L)
        self._spec_accept_hist = reg.histogram(
            "pt_serve_spec_acceptance_rate",
            "per-slot per-verify accepted/proposed fraction",
            labels=L,
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self._spec_rate = reg.gauge(
            "pt_serve_spec_acceptance_rate_cum",
            "cumulative accepted/proposed draft-token ratio", L)
        self._req_tpot = reg.histogram(
            "pt_serve_request_tpot_ms",
            "per-request mean time per output token, computed at "
            "finish over the request's whole decode (admit -> last "
            "token) — the per-REQUEST latency SLOs are written "
            "against, vs pt_serve_tpot_ms's per-dispatch view",
            labels=L, buckets=exp_buckets(0.25, 2.0, 18))
        self._cancelled = reg.counter(
            "pt_serve_requests_cancelled_total",
            "requests cancelled (queued or mid-flight) — their slots "
            "and KV pages were released without finishing", L)
        self._timeouts = reg.counter(
            "pt_serve_requests_timeout_total",
            "requests expired by their deadline (queued or mid-"
            "flight) — slots, KV pages and prefix refs were released",
            L)
        self._failed = reg.counter(
            "pt_serve_requests_failed_total",
            "requests finished as failed after exhausting crash-"
            "recovery replay retries", L)
        self._recoveries = reg.counter(
            "pt_serve_recoveries_total",
            "quarantined steps: a decode/verify/prefill fault was "
            "caught, the step's device effects were discarded and the "
            "affected in-flight requests were re-queued for "
            "deterministic replay", L)
        self._retries = reg.counter(
            "pt_serve_retries_total",
            "request replay re-queues charged by quarantined steps "
            "(bounded per request by max_retries)", L)
        self._faults = reg.counter(
            "pt_serve_faults_injected_total",
            "fault-injector fires observed at the engine's dispatch "
            "seams, by site (PT_FLAGS_fault_inject)",
            ("engine", "site"))
        self._deg_level = reg.gauge(
            "pt_serve_degradation_level",
            "graceful-degradation ladder level: 0 normal, 1 shed "
            "batch-class admissions, 2 + admission throttled, 3 + "
            "spec decode and prefix-cache adoption disabled "
            "(min_service)", L)
        self._draining = reg.gauge(
            "pt_serve_draining",
            "1 while the engine drains (admission stopped, in-flight "
            "running to completion)", L)
        self._hbm = reg.gauge(
            "pt_serve_hbm_bytes",
            "live HBM residency by component, from array-metadata "
            "nbytes (kv_pool, kv_scales [int8 dequant rows], "
            "weights_<dtype>, prefix_store [contiguous materialized "
            "blocks]) — observability/profiling.hbm_accounting",
            ("engine", "component"))
        self._hbm_peak = reg.gauge(
            "pt_serve_hbm_bytes_peak",
            "high-watermark of pt_serve_hbm_bytes per component this "
            "window", ("engine", "component"))
        # component labels seen so far — window_reset must zero each
        # peak series this engine created (labels aren't enumerable
        # from the gauge side)
        self._hbm_components: set = set()
        self._preempted = reg.counter(
            "pt_serve_preemptions_total",
            "active requests preempted by the scheduler policy "
            "(slot/pages released, request re-queued at the front for "
            "deterministic prompt+history replay — the SLO-fair "
            "scheduler's anti-starvation lever)", L)
        LS = ("engine", "slo", "tenant")
        self._req_device = reg.histogram(
            "pt_serve_request_device_ms",
            "per-request ATTRIBUTED device time (ms), recorded at "
            "finish: each step's measured program-ms (ProgramProfiler "
            "sample; sync-wall estimate on unsampled steps) split "
            "across the requests the step advanced, proportional to "
            "tokens advanced — the measured per-token cost the "
            "Tensix-style bytes-per-token models are laid against. "
            "slo='untracked' for SLO-less requests; tenant='-' for "
            "untagged traffic",
            labels=LS, buckets=exp_buckets(0.05, 2.0, 22))
        # (slo, tenant) label pairs this engine recorded costs under —
        # window_reset must clear each series' percentile window
        # (labels aren't enumerable from the histogram side; the hbm
        # pattern)
        self._cost_slos: set = set()
        self._slo_met = reg.counter(
            "pt_serve_slo_met_total",
            "finished requests that met every SLO target of their "
            "class (TTFT and per-request TPOT)", LS)
        self._slo_violated = reg.counter(
            "pt_serve_slo_violated_total",
            "finished requests that missed an SLO target", LS)
        self._slo_goodput = reg.gauge(
            "pt_serve_slo_goodput",
            "met / (met + violated) for SLO-tracked finishes — the "
            "fraction of traffic the engine is serving within target",
            LS)

    def _lab(self) -> dict:
        return {"engine": self.engine_id}

    def _sum_engine(self, metric) -> float:
        """Total over this engine's series of a tenant-labeled metric
        (``series()`` copies under the registry lock — safe from any
        thread); the snapshot keeps its engine-level aggregate while
        the per-tenant series stay scrapeable."""
        i = metric.label_names.index("engine")
        return sum(v for k, v in metric.series().items()
                   if k[i] == self.engine_id)

    # ---------------- hooks ----------------
    def on_submit(self, queue_depth: int):
        self._submitted.inc(**self._lab())
        self._note_queue(queue_depth)

    def on_admit(self, ttft_ms: Optional[float]):
        lab = self._lab()
        self._admitted.inc(**lab)
        self._tokens.inc(**lab)  # prefill samples the first output token
        if ttft_ms is not None:
            self._ttft.observe(ttft_ms, **lab)

    def on_finish(self, tpot_ms: Optional[float] = None):
        lab = self._lab()
        self._finished.inc(**lab)
        if tpot_ms is not None:
            self._req_tpot.observe(tpot_ms, **lab)

    def on_cancel(self):
        self._cancelled.inc(**self._lab())

    def on_timeout(self):
        self._timeouts.inc(**self._lab())

    def on_failed(self):
        self._failed.inc(**self._lab())

    def on_recovery(self, requeued: int):
        """One quarantined step (``requeued`` requests re-queued for
        replay; per-request retries counted via ``on_retry``)."""
        self._recoveries.inc(**self._lab())

    def on_retry(self):
        self._retries.inc(**self._lab())

    def on_readmit(self):
        """A replayed request re-admitted: its re-prefill sampled one
        fresh output token (TTFT/admitted counted only at the FIRST
        admission)."""
        self._tokens.inc(**self._lab())

    def on_fault(self, site: str):
        self._faults.inc(**dict(self._lab(), site=site))

    def on_degradation(self, level: int):
        self._deg_level.set(level, **self._lab())

    def on_drain(self, active: bool):
        self._draining.set(1 if active else 0, **self._lab())

    def on_slo(self, slo: str, met: bool, tenant: str = "-"):
        """One SLO-tracked request finished: ``met`` is its
        attainment. The goodput gauge is derived from THIS series' own
        met/violated counters, so every (class, tenant) pair reports
        its own fraction — per-tenant attainment is the starvation
        evidence the SLO-fair scheduler is ranked on, and a starved
        tenant must never read the healthy tenant's blended number."""
        lab = dict(self._lab(), slo=slo, tenant=tenant)
        (self._slo_met if met else self._slo_violated).inc(**lab)
        m = self._slo_met.value(**lab)
        v = self._slo_violated.value(**lab)
        self._slo_goodput.set(m / (m + v), **lab)

    def on_preempt(self):
        self._preempted.inc(**self._lab())

    def on_prefix(self, hit_tokens: int, prompt_tokens: int,
                  cached_blocks: int, tenant: str = "-"):
        lab = self._lab()
        labt = dict(lab, tenant=tenant)
        (self._pfx_hits if hit_tokens > 0
         else self._pfx_misses).inc(**labt)
        if hit_tokens > 0:
            self._pfx_hit_tokens.inc(hit_tokens, **labt)
        self._pfx_prompt_tokens.inc(prompt_tokens, **labt)
        self._pfx_cached.set(cached_blocks, **lab)

    def on_prefix_evict(self, n: int = 1,
                        cached_blocks: Optional[int] = None):
        lab = self._lab()
        self._pfx_evict.inc(n, **lab)
        if cached_blocks is not None:
            # keep the residency gauge honest between admissions —
            # evictions under pure decode pressure must show up too
            self._pfx_cached.set(cached_blocks, **lab)

    def on_hbm(self, components: dict):
        """Refresh the HBM residency gauges + watermarks (component →
        bytes, from ``profiling.hbm_accounting``)."""
        for comp, nbytes in list(components.items()):
            lab = dict(self._lab(), component=comp)
            self._hbm.set(nbytes, **lab)
            self._hbm_peak.set_max(nbytes, **lab)
            self._hbm_components.add(comp)

    def on_request_cost(self, slo: str, device_ms: float,
                        tenant: str = "-"):
        """One finished request's attributed device cost (ms)."""
        self._req_device.observe(device_ms, slo=slo, tenant=tenant,
                                 **self._lab())
        self._cost_slos.add((slo, tenant))

    def on_spec_slot(self, proposed: int, accepted: int):
        """One slot's outcome in one verify pass — feeds the
        acceptance-rate histogram (per-slot granularity: a 100%-accept
        slot and a 0%-accept slot must not average into one bland
        mid-bucket observation)."""
        if proposed > 0:
            self._spec_accept_hist.observe(accepted / proposed,
                                           **self._lab())

    def on_spec_verify(self, proposed: int, accepted: int,
                       cum_accepted: int, cum_proposed: int):
        lab = self._lab()
        self._spec_verify.inc(**lab)
        if proposed > 0:
            self._spec_proposed.inc(proposed, **lab)
        if accepted > 0:
            self._spec_accepted.inc(accepted, **lab)
        if cum_proposed > 0:
            self._spec_rate.set(cum_accepted / cum_proposed, **lab)

    def on_spec_fallback(self):
        self._spec_fallback.inc(**self._lab())

    def on_tokens(self, n_tokens: int, wall_ms: float):
        if n_tokens <= 0:
            return
        lab = self._lab()
        self._tokens.inc(n_tokens, **lab)
        self._tpot.observe(wall_ms / n_tokens, **lab)

    def _note_queue(self, depth: int):
        lab = self._lab()
        self._queue.set(depth, **lab)
        self._queue_peak.set_max(depth, **lab)

    def on_state(self, queue_depth: int, occupancy: float,
                 kv_used: float, kv_total: float):
        lab = self._lab()
        self._note_queue(queue_depth)
        self._occ.set(occupancy, **lab)
        self._occ_peak.set_max(occupancy, **lab)
        self._kv_used.set(kv_used, unit="used", **lab)
        self._kv_used.set(kv_total, unit="total", **lab)
        util = kv_used / kv_total if kv_total else 0.0
        self._kv.set(util, **lab)
        self._kv_peak.set_max(util, **lab)

    # ---------------- read side ----------------
    def window_percentiles(self) -> dict:
        """Current histogram window-percentiles for the time-series
        collector (None entries while a window has no observations —
        the sample records the absence honestly)."""
        lab = self._lab()
        return {
            "ttft_ms_p50": self._ttft.percentile(50, **lab),
            "ttft_ms_p99": self._ttft.percentile(99, **lab),
            "tpot_ms_p50": self._tpot.percentile(50, **lab),
            "request_tpot_ms_p99": self._req_tpot.percentile(99, **lab),
        }

    def snapshot(self) -> dict:
        lab = self._lab()
        return {
            "engine": self.engine_id,
            "ttft_ms": {
                "p50": self._ttft.percentile(50, **lab),
                "p90": self._ttft.percentile(90, **lab),
                "p99": self._ttft.percentile(99, **lab),
                "count": self._ttft.window_len(**lab),
            },
            "tpot_ms": {
                "p50": self._tpot.percentile(50, **lab),
                "p90": self._tpot.percentile(90, **lab),
            },
            "request_tpot_ms": {
                "p50": self._req_tpot.percentile(50, **lab),
                "p99": self._req_tpot.percentile(99, **lab),
                "count": self._req_tpot.window_len(**lab),
            },
            "queue_depth": {
                "current": self._queue.value(**lab),
                "peak": self._queue_peak.value(**lab),
            },
            "batch_occupancy": {
                "current": self._occ.value(**lab),
                "peak": self._occ_peak.value(**lab),
            },
            "kv_pool": {
                "used": self._kv_used.value(unit="used", **lab),
                "total": self._kv_used.value(unit="total", **lab),
                "utilization": self._kv.value(**lab),
                "peak_utilization": self._kv_peak.value(**lab),
            },
            "requests": {
                "submitted": self._submitted.value(**lab),
                "admitted": self._admitted.value(**lab),
                "finished": self._finished.value(**lab),
                "cancelled": self._cancelled.value(**lab),
            },
            "tokens_generated": self._tokens.value(**lab),
            "prefix_cache": {
                "hits": self._sum_engine(self._pfx_hits),
                "misses": self._sum_engine(self._pfx_misses),
                "hit_tokens": self._sum_engine(self._pfx_hit_tokens),
                "prompt_tokens": self._sum_engine(
                    self._pfx_prompt_tokens),
                "evictions": self._pfx_evict.value(**lab),
                "cached_blocks": self._pfx_cached.value(**lab),
            },
            "spec_decode": {
                "proposed_tokens": self._spec_proposed.value(**lab),
                "accepted_tokens": self._spec_accepted.value(**lab),
                "verify_calls": self._spec_verify.value(**lab),
                "fallback_steps": self._spec_fallback.value(**lab),
                "acceptance_rate": self._spec_rate.value(**lab),
            },
            # resilience counters are NOT duplicated here: the
            # engine's metrics_snapshot() attaches its host-side
            # resilience_snapshot() (one source, telemetry-off-safe)
        }

    def window_reset(self):
        """Clear percentile windows + this engine's peaks (cumulative
        counters and the Prometheus bucket totals keep running)."""
        lab = self._lab()
        self._ttft.reset_window(**lab)
        self._tpot.reset_window(**lab)
        self._req_tpot.reset_window(**lab)
        self._spec_accept_hist.reset_window(**lab)
        for slo, tenant in list(self._cost_slos):
            self._req_device.reset_window(slo=slo, tenant=tenant,
                                          **lab)
        self._queue_peak.set(0, **lab)
        self._occ_peak.set(0.0, **lab)
        self._kv_peak.set(0.0, **lab)
        for comp in list(self._hbm_components):
            self._hbm_peak.set(0, component=comp, **lab)


_ROUTER_SEQ = itertools.count()


class RouterTelemetry:
    """Fleet front-door telemetry for the multi-engine router
    (``inference/router.py``): per-replica routing/failover counters
    and breaker-state gauges, correlated to each replica engine's own
    ``pt_serve_*`` series by the shared process registry. All hooks
    are host bookkeeping the router already holds — zero device
    traffic."""

    def __init__(self):
        reg = get_registry()
        self.router_id = str(next(_ROUTER_SEQ))
        L = ("router",)
        LR = ("router", "replica")
        self._routed = reg.counter(
            "pt_router_requests_routed_total",
            "requests placed on a replica by the front door", LR)
        self._affinity = reg.counter(
            "pt_router_affinity_routed_total",
            "placements steered by prefix affinity (the chosen "
            "replica's store already held >= 1 prompt block)", L)
        self._sheds = reg.counter(
            "pt_router_requests_held_total",
            "admissions the router held in its own queue because no "
            "replica was routable (all saturated, draining, or "
            "breaker-open) — fleet-level shedding, deferral not drop",
            L)
        self._failovers = reg.counter(
            "pt_router_failovers_total",
            "whole-replica failure events (crash, hang-opened "
            "breaker, fault-opened breaker) that triggered "
            "cross-replica failover", LR)
        self._reclaimed = reg.counter(
            "pt_router_reclaimed_requests_total",
            "in-flight + queued requests reclaimed from a failed "
            "replica's host token ledger", LR)
        self._replayed = reg.counter(
            "pt_router_replayed_requests_total",
            "reclaimed requests re-admitted onto a surviving replica "
            "for deterministic ledger replay", L)
        self._held_timeouts = reg.counter(
            "pt_router_requests_timeout_total",
            "router-held requests whose deadline expired before any "
            "replica could take them (engine-side timeouts count "
            "under pt_serve_requests_timeout_total)", L)
        self._held_cancels = reg.counter(
            "pt_router_requests_cancelled_total",
            "router-held requests cancelled before placement "
            "(engine-side cancels count under "
            "pt_serve_requests_cancelled_total)", L)
        self._breaker_opens = reg.counter(
            "pt_router_breaker_opens_total",
            "circuit-breaker open transitions per replica", LR)
        self._breaker_state = reg.gauge(
            "pt_router_breaker_state",
            "per-replica breaker state: 0 closed, 1 open, 2 half-open "
            "(canary)", LR)
        self._routable = reg.gauge(
            "pt_router_replicas_routable",
            "replicas currently accepting new traffic (breaker "
            "closed, not draining)", L)
        self._qdepth = reg.gauge(
            "pt_router_queue_depth",
            "requests held at the router awaiting a routable replica",
            L)

    def _lab(self) -> dict:
        return {"router": self.router_id}

    def on_route(self, replica: int, affinity: bool):
        self._routed.inc(router=self.router_id, replica=str(replica))
        if affinity:
            self._affinity.inc(**self._lab())

    def on_hold(self, queue_depth: int):
        self._sheds.inc(**self._lab())
        self._qdepth.set(queue_depth, **self._lab())

    def on_failover(self, replica: int, reclaimed: int):
        lab = dict(self._lab(), replica=str(replica))
        self._failovers.inc(**lab)
        if reclaimed > 0:
            self._reclaimed.inc(reclaimed, **lab)

    def on_replay(self, n: int = 1):
        self._replayed.inc(n, **self._lab())

    def on_held_timeout(self):
        self._held_timeouts.inc(**self._lab())

    def on_held_cancel(self):
        self._held_cancels.inc(**self._lab())

    def on_breaker(self, replica: int, state: int, opened: bool):
        lab = dict(self._lab(), replica=str(replica))
        self._breaker_state.set(state, **lab)
        if opened:
            self._breaker_opens.inc(**lab)

    def on_fleet_state(self, routable: int, queue_depth: int):
        lab = self._lab()
        self._routable.set(routable, **lab)
        self._qdepth.set(queue_depth, **lab)

    def _sum(self, metric) -> float:
        """Total over this router's per-replica series (``series()``
        copies under the registry lock — safe from any thread)."""
        i = metric.label_names.index("router")
        return sum(v for k, v in metric.series().items()
                   if k[i] == self.router_id)

    def snapshot(self) -> dict:
        lab = self._lab()
        return {
            "router": self.router_id,
            "routed": self._sum(self._routed),
            "affinity_routed": self._affinity.value(**lab),
            "held": self._sheds.value(**lab),
            "failovers": self._sum(self._failovers),
            "reclaimed": self._sum(self._reclaimed),
            "replayed": self._replayed.value(**lab),
            "held_timeouts": self._held_timeouts.value(**lab),
            "held_cancels": self._held_cancels.value(**lab),
            "breaker_opens": self._sum(self._breaker_opens),
            "replicas_routable": self._routable.value(**lab),
            "queue_depth": self._qdepth.value(**lab),
        }
