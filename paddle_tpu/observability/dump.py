"""One-shot telemetry snapshot CLI.

``python -m paddle_tpu.observability.dump`` prints a JSON snapshot of
the in-process registry, the per-call-site collective log, and per-
device ``memory_stats()`` — the no-debugger inspection path. For a
*running* server, ``--url http://host:port/metrics`` scrapes its
Prometheus endpoint instead (a separate process cannot see this
process's registry).

Options:
  --prometheus   emit Prometheus text format instead of JSON
  --no-device    skip device queries (safe on a wedged accelerator)
  --url URL      fetch a live endpoint and print it (point it at
                 /metrics for exposition text, or at /trace for a
                 server's Chrome trace JSON)
  --trace        emit the in-process lifecycle tracers as Chrome
                 trace-event JSON (load in Perfetto / chrome://tracing)
  --trace-jsonl  emit the raw tracer events as JSON-lines instead
  --fleet        fleet export: for every in-process EngineRouter, its
                 host-side fleet snapshot plus the MERGED router +
                 replica Chrome trace (failed-over rids joined by flow
                 events). For a *running* fleet server, point --url at
                 /trace?fleet=1 instead
  --timeline     every in-process TimeSeriesStore's retained windows
                 (PT_FLAGS_timeseries) as JSON. For a *running*
                 server, point --url at /timeline instead
"""

from __future__ import annotations

import argparse
import json
import sys


def _device_memory():
    import jax

    out = []
    for d in jax.devices():
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        out.append({
            "device": f"{d.platform}:{d.id}",
            "kind": getattr(d, "device_kind", ""),
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        })
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.dump",
        description="one-shot paddle_tpu telemetry snapshot")
    ap.add_argument("--prometheus", action="store_true",
                    help="Prometheus text format instead of JSON")
    ap.add_argument("--no-device", action="store_true",
                    help="skip jax device queries")
    ap.add_argument("--url", default=None,
                    help="scrape a live /metrics (or /trace) endpoint "
                         "instead")
    ap.add_argument("--trace", action="store_true",
                    help="Chrome trace-event JSON of the in-process "
                         "lifecycle tracers (Perfetto-loadable)")
    ap.add_argument("--trace-jsonl", action="store_true",
                    help="raw tracer events as JSON-lines")
    ap.add_argument("--fleet", action="store_true",
                    help="per-fleet snapshot + merged router+replica "
                         "Chrome trace (flow-correlated failovers)")
    ap.add_argument("--timeline", action="store_true",
                    help="every in-process time-series store's "
                         "retained windows (PT_FLAGS_timeseries) as "
                         "JSON — for a running server, point --url at "
                         "/timeline instead")
    args = ap.parse_args(argv)

    if args.url:
        from urllib.request import urlopen

        with urlopen(args.url, timeout=10) as resp:
            sys.stdout.write(resp.read().decode("utf-8", "replace"))
        return 0

    from . import comm, registry, timeseries, tracing

    if args.timeline:
        out = [s.snapshot() for s in timeseries.stores()]
        out.sort(key=lambda s: s["label"])
        json.dump(out, sys.stdout, default=str)
        sys.stdout.write("\n")
        if not out:
            print("dump --timeline: no in-process TimeSeriesStore "
                  "(PT_FLAGS_timeseries off, or no engine "
                  "constructed; use --url http://host:port/timeline "
                  "for a running server)", file=sys.stderr)
        return 0
    if args.fleet:
        out = []
        for fleet in tracing.fleets():
            out.append({
                # host counters — available with telemetry off
                "fleet_snapshot": fleet.fleet_snapshot(),
                "trace": tracing.fleet_chrome_trace(fleet),
            })
        json.dump(out, sys.stdout, default=str)
        sys.stdout.write("\n")
        if not out:
            print("dump --fleet: no in-process EngineRouter "
                  "registered (use --url http://host:port/trace?"
                  "fleet=1 for a running fleet server)",
                  file=sys.stderr)
        return 0
    if args.trace:
        json.dump(tracing.chrome_trace(), sys.stdout, default=str)
        sys.stdout.write("\n")
        return 0
    if args.trace_jsonl:
        out = tracing.jsonl()
        sys.stdout.write(out + ("\n" if out else ""))
        return 0

    if args.prometheus:
        sys.stdout.write(registry.global_registry().prometheus_text())
        return 0

    snap = {
        "telemetry_enabled": registry.enabled(),
        "metrics": registry.global_registry().snapshot(),
        "collectives": comm.comm_log(),
    }
    if not args.no_device:
        snap["device_memory"] = _device_memory()
    json.dump(snap, sys.stdout, indent=1, default=str)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
